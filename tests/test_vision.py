"""Vision zoo / transforms / datasets tests (reference model:
test/legacy_test/test_vision_models.py, test_transforms.py)."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, models, transforms
from paddle_tpu.vision.transforms import functional as TF


def n(x):
    return np.asarray(x._value if hasattr(x, "_value") else x)


def _rand(shape):
    return paddle.to_tensor(
        np.random.RandomState(0).randn(*shape).astype(np.float32) * 0.1)


class TestModelZoo:
    @pytest.mark.parametrize("ctor,size,classes", [
        (models.LeNet, 28, 10),
        (lambda num_classes: models.mobilenet_v2(
            scale=0.25, num_classes=num_classes), 96, 7),
        (lambda num_classes: models.mobilenet_v3_small(
            scale=0.5, num_classes=num_classes), 96, 7),
        (lambda num_classes: models.shufflenet_v2_x0_25(
            num_classes=num_classes), 96, 7),
        (lambda num_classes: models.squeezenet1_1(
            num_classes=num_classes), 96, 7),
    ])
    def test_small_model_forward(self, ctor, size, classes):
        model = ctor(num_classes=classes)
        model.eval()
        ch = 1 if isinstance(model, models.LeNet) else 3
        out = model(_rand((2, ch, size, size)))
        assert tuple(out.shape) == (2, classes)
        assert np.isfinite(n(out)).all()

    def test_mobilenet_v1(self):
        m = models.mobilenet_v1(scale=0.25, num_classes=5)
        m.eval()
        out = m(_rand((1, 3, 96, 96)))
        assert tuple(out.shape) == (1, 5)

    def test_densenet(self):
        # smallest input the stem supports — keeps eager CPU time bounded
        m = models.densenet121(num_classes=6)
        m.eval()
        out = m(_rand((1, 3, 32, 32)))
        assert tuple(out.shape) == (1, 6)
        assert np.isfinite(n(out)).all()

    def test_googlenet_eval_and_train_aux(self):
        m = models.googlenet(num_classes=4)
        m.eval()
        out, aux1, aux2 = m(_rand((1, 3, 64, 64)))
        assert tuple(out.shape) == (1, 4)
        assert aux1 is None and aux2 is None
        m.train()
        out, aux1, aux2 = m(_rand((1, 3, 64, 64)))
        assert tuple(aux1.shape) == (1, 4)
        assert tuple(aux2.shape) == (1, 4)

    def test_inception_v3(self):
        m = models.inception_v3(num_classes=3)
        m.eval()
        out = m(_rand((1, 3, 128, 128)))
        assert tuple(out.shape) == (1, 3)

    def test_vgg_alexnet(self):
        for m in [models.vgg11(num_classes=3), models.alexnet(num_classes=3)]:
            m.eval()
            out = m(_rand((1, 3, 96, 96)))
            assert tuple(out.shape) == (1, 3)
            assert np.isfinite(n(out)).all()

    def test_vgg_nonstandard_size(self):
        # adaptive pool before the classifier handles any input size
        m = models.vgg11(num_classes=3)
        m.eval()
        out = m(_rand((1, 3, 80, 80)))
        assert tuple(out.shape) == (1, 3)

    def test_shufflenet_backward(self):
        # channel_shuffle/split must stay on the autograd tape
        m = models.shufflenet_v2_x0_25(num_classes=4)
        m.train()
        out = m(_rand((1, 3, 64, 64)))
        loss = out.sum()
        loss.backward()
        grads = [p.grad for p in m.parameters()]
        assert any(g is not None and np.abs(n(g)).sum() > 0
                   for g in grads)


class TestTransforms:
    def setup_method(self):
        rng = np.random.RandomState(0)
        self.img = rng.randint(0, 255, (32, 48, 3), dtype=np.uint8)

    def test_functional_basics(self):
        assert TF.hflip(self.img)[0, 0].tolist() == \
            self.img[0, -1].tolist()
        assert TF.vflip(self.img)[0, 0].tolist() == \
            self.img[-1, 0].tolist()
        r = TF.resize(self.img, (16, 24))
        assert r.shape == (16, 24, 3)
        r2 = TF.resize(self.img, 16)  # short side
        assert r2.shape == (16, 24, 3)
        c = TF.center_crop(self.img, 16)
        assert c.shape == (16, 16, 3)
        p = TF.pad(self.img, 2)
        assert p.shape == (36, 52, 3)
        t = TF.to_tensor(self.img)
        assert tuple(t.shape) == (3, 32, 48)
        assert 0.0 <= float(n(t).min()) and float(n(t).max()) <= 1.0

    def test_color_ops(self):
        b = TF.adjust_brightness(self.img, 1.5)
        assert b.dtype == np.uint8 and b.mean() >= self.img.mean()
        TF.adjust_contrast(self.img, 0.5)
        TF.adjust_saturation(self.img, 2.0)
        h = TF.adjust_hue(self.img, 0.1)
        assert h.shape == self.img.shape
        # hue=0 is identity (within rounding)
        h0 = TF.adjust_hue(self.img, 0.0)
        assert np.abs(h0.astype(int) - self.img.astype(int)).max() <= 1

    def test_normalize_matches_numpy(self):
        t = TF.to_tensor(self.img)
        out = TF.normalize(t, [0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
        ref = (n(t) - 0.5) / 0.5
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_compose_pipeline(self):
        pipe = transforms.Compose([
            transforms.Resize(40),
            transforms.RandomCrop(32),
            transforms.RandomHorizontalFlip(0.5),
            transforms.ColorJitter(0.1, 0.1, 0.1, 0.1),
            transforms.ToTensor(),
            transforms.Normalize([0.5] * 3, [0.5] * 3),
        ])
        out = pipe(self.img)
        assert out.shape == (3, 32, 32)

    def test_rotate_and_grayscale(self):
        rot = TF.rotate(self.img, 90)
        assert rot.shape == self.img.shape
        # expand grows the canvas; 90° of a 32x48 → 48x32
        rexp = TF.rotate(self.img, 90, expand=True)
        assert rexp.shape[:2] == (48, 32)
        # bilinear at 0° is identity
        rb = TF.rotate(self.img, 0, interpolation='bilinear')
        np.testing.assert_array_equal(rb, self.img)
        g = TF.to_grayscale(self.img)
        assert g.shape == (32, 48, 1)
        g3 = TF.to_grayscale(self.img, 3)
        assert g3.shape == (32, 48, 3)

    def test_random_erasing(self):
        t = transforms.RandomErasing(prob=1.0, value=0)
        out = t(self.img.copy())
        assert (out == 0).any()


class TestDatasets:
    def _write_mnist(self, tmpdir, n_img=10):
        rng = np.random.RandomState(0)
        imgs = rng.randint(0, 255, (n_img, 28, 28), dtype=np.uint8)
        labels = rng.randint(0, 10, (n_img,), dtype=np.uint8)
        ip = os.path.join(tmpdir, "train-images-idx3-ubyte.gz")
        lp = os.path.join(tmpdir, "train-labels-idx1-ubyte.gz")
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n_img, 28, 28))
            f.write(imgs.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, n_img))
            f.write(labels.tobytes())
        return ip, lp, imgs, labels

    def test_mnist(self, tmp_path):
        ip, lp, imgs, labels = self._write_mnist(str(tmp_path))
        ds = datasets.MNIST(image_path=ip, label_path=lp, mode="train")
        assert len(ds) == 10
        img, label = ds[3]
        np.testing.assert_array_equal(img, imgs[3])
        assert label[0] == labels[3]
        # with transform
        ds2 = datasets.MNIST(image_path=ip, label_path=lp,
                             transform=transforms.ToTensor())
        img2, _ = ds2[0]
        assert tuple(img2.shape) == (1, 28, 28)

    def test_cifar10(self, tmp_path):
        rng = np.random.RandomState(0)
        data = rng.randint(0, 255, (20, 3072), dtype=np.uint8)
        labels = rng.randint(0, 10, (20,)).tolist()
        batch = {b"data": data, b"labels": labels}
        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        for name in [f"data_batch_{i}" for i in range(1, 6)] + \
                ["test_batch"]:
            with open(d / name, "wb") as f:
                pickle.dump(batch, f)
        tar = tmp_path / "cifar-10-python.tar.gz"
        with tarfile.open(tar, "w:gz") as t:
            t.add(d, arcname="cifar-10-batches-py")
        ds = datasets.Cifar10(data_file=str(tar), mode="test")
        assert len(ds) == 20
        img, label = ds[0]
        assert img.shape == (32, 32, 3)

    def test_folder(self, tmp_path):
        for cls in ["cat", "dog"]:
            (tmp_path / cls).mkdir()
            for i in range(3):
                np.save(tmp_path / cls / f"{i}.npy",
                        np.zeros((8, 8, 3), np.uint8))
        ds = datasets.DatasetFolder(str(tmp_path))
        assert len(ds) == 6
        assert ds.classes == ["cat", "dog"]
        img, target = ds[0]
        assert img.shape == (8, 8, 3) and target == 0
        flat = datasets.ImageFolder(str(tmp_path))
        assert len(flat) == 6
