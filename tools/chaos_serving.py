#!/usr/bin/env python
"""Deterministic chaos harness for the ServingEngine (ISSUE 4 gate).

Drives the engine through a seeded randomized schedule of arrivals,
cancellations and injected faults — allocator OOMs, dispatch
exceptions, collection faults, latency spikes — while asserting
``PagedKVCache.debug_check()`` after EVERY scheduler step, then replays
the identical arrival schedule on a fault-free engine and demands that
every request the chaos engine completed ("done") produced
TOKEN-IDENTICAL output. Requests the chaos run cancelled / failed /
shed are the "faulted" set and are reported, not compared.

The run is deterministic end to end (one seed feeds the workload
generator and the ChaosMonkey; sampling is greedy), so a failure here
is a reproducible bug, not a flake.

    python tools/chaos_serving.py                      # 200-step run
    python tools/chaos_serving.py --steps 60 --require-events
    python tools/chaos_serving.py --seed 3 --p-dispatch 0.1

Exit code is non-zero on: an engine crash, a debug_check violation, a
token mismatch, or (with --require-events) a schedule that failed to
exercise at least one OOM-driven preemption, one injected dispatch
fault AND one cancellation/abort. Prints one JSON summary line
(BENCH-style extra dict).

--dp R (ISSUE 11) swaps the single engine for an R-replica
prefix-affinity fleet Router: every replica gets its own seeded
background monkey AND replica 0 is WEDGED at a seeded mid-run step
(ChaosMonkey.wedge — persistent dispatch+collect failure). The Router
must trip its circuit breaker, drain the wedged replica and
redistribute its queue as prompt+generated-history recomputes;
--require-events then demands >=1 replica failover and >=1
migrated-request COMPLETION on top of the dispatch-fault/cancellation
events, and token identity covers surviving and migrated requests
alike vs a fault-free fleet replay.

--trace-out PATH (ISSUE 12) runs the CHAOS leg with serving telemetry
on (one shared Tracer across the engine/fleet — per-request spans,
per-step dispatch events, injected faults) and writes the
flight-recorder Perfetto export to PATH whether the run passes or
crashes, so every red gate run ships its own post-mortem timeline
(tools/trace_report.py summarizes it). The fault-free replay stays
untraced — its token identity against the traced chaos run doubles as
proof that tracing never changes scheduling or sampling.

--seal-programs (ISSUE 14) grid-compiles the chaos engine's reachable
program set (ServingEngine.warmup_programs — direct invocation, no
PRNG, no scheduler state) and SEALS it before any traffic, bounding
ragged_idle_cap (default 8) on BOTH runs so the grid is closed. From
then on ANY XLA retrace the fault schedule provokes lands in
``unexpected_recompiles`` and FAILS the leg — the runtime twin of
flightcheck's static FC2xx rules: a schedule path that quietly
compiles mid-run (an unexpected shape, a weak-type flip, an unstable
cache key) is a gate failure, not an ITL spike.

--multi-step k (ISSUE 16) runs the schedule with fused k-step decode
windows (implies ragged): every OOM preemption neutralizes a whole
fused window, cancellations land at the next k-boundary, debug_check
runs per boundary, and --require-events additionally demands >=1
fused window actually dispatched (multi_step_windows >= 1) so the leg
cannot silently spend the whole schedule in the prefill regime.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def build_engine(model, args, tracer=None):
    from paddle_tpu.inference import ServingEngine, SpecConfig
    # getattr defaults: programmatic callers (the slow fault-tolerance
    # test builds a bare Namespace) predate the
    # --ragged/--tp/--spec/--lora flags and must keep running on the
    # dense single-chip engine
    lora = None
    if getattr(args, "lora", False):
        from paddle_tpu.inference import AdapterRegistry
        # rank-1 adapters keep the page footprint small enough that a
        # tight pool constantly evicts cold adapters — exactly the
        # S-LoRA pressure path this leg exists to exercise. Both the
        # chaos run and the fault-free replay build IDENTICAL
        # registries (seeded), so token identity is well-defined.
        lora = AdapterRegistry(rank=1)
        lora.register_random("a0", seed=101, scale=0.1)
        lora.register_random("a1", seed=102, scale=0.1)
        # a2 is deliberately RARE traffic: it spends long stretches
        # cold/parked, so pool pressure actually evicts it and its
        # next request exercises the refault path (the
        # adapter_eviction event --require-events demands)
        lora.register_random("a2", seed=103, scale=0.1)
    return ServingEngine(
        model, max_batch_size=3, num_blocks=args.num_blocks,
        block_size=8, prompt_buckets=(8, 16, 32), chunk_size=4,
        prefill_chunk=8,
        admission="optimistic",
        max_dispatch_retries=args.retries,
        retry_backoff_s=0.0,
        ragged=getattr(args, "ragged", False)
        or getattr(args, "tp", 1) > 1,
        tp=getattr(args, "tp", 1),
        spec_decode=SpecConfig(draft_len=4)
        if getattr(args, "spec", False) else None,
        lora=lora, tracer=tracer,
        kv_quant=getattr(args, "kv_quant", None),
        # a bounded idle-drain width closes the reachable (T, W)
        # program grid, which is what makes --seal-programs assertable
        # (ISSUE 14); both runs share the bound so schedules match
        ragged_idle_cap=getattr(args, "ragged_idle_cap", None),
        multi_step=getattr(args, "multi_step", 1))


def build_fleet(model, args, tracer=None, transport="inproc"):
    """The --dp leg's fleet (ISSUE 11): R single-chip replicas behind
    the prefix-affinity Router, each with the same tight-geometry
    engine the single-engine legs use. Both the chaos run and the
    fault-free replay build IDENTICAL fleets, so token identity of
    surviving AND migrated requests is well-defined (all-greedy
    workload; routing may differ between the runs — greedy outputs are
    replica-independent by the cross-replica identity contract).
    ``transport="process"`` (ISSUE 19) puts each replica's engine in a
    spawned worker process — the dp_proc leg's crash-isolated fleet."""
    from paddle_tpu.inference.fleet import Router
    return Router(
        model, dp=args.dp, transport=transport,
        max_batch_size=3, num_blocks=args.num_blocks, block_size=8,
        prompt_buckets=(8, 16, 32), chunk_size=4, prefill_chunk=8,
        admission="optimistic", max_dispatch_retries=args.retries,
        retry_backoff_s=0.0, ragged=getattr(args, "ragged", False),
        kv_quant=getattr(args, "kv_quant", None), tracer=tracer,
        ragged_idle_cap=getattr(args, "ragged_idle_cap", None))


def gen_workload(args):
    """Seeded arrival/cancel schedule, independent of engine state so
    the chaos and fault-free runs see the same traffic."""
    rng = np.random.RandomState(args.seed)
    # shared block-aligned prefix templates: ~half the prompts open
    # with one of these, so requests form splice dependencies (prefix
    # cache hits, splice-pending readers) and cancels/preemptions hit
    # writers with dependent readers — the riskiest recovery paths
    templates = [rng.randint(0, args.vocab, (24,)).astype(np.int32)
                 for _ in range(2)]
    arrivals = []   # (step, prompt, max_new, adapter_id, allowed)
    lora = getattr(args, "lora", False)
    step = 0
    while len(arrivals) < args.requests:
        step += int(rng.randint(1, max(2, args.steps // args.requests)))
        plen = int(rng.choice([5, 8, 12, 16, 21, 32]))
        prompt = rng.randint(0, args.vocab, plen).astype(np.int32)
        if rng.random_sample() < 0.5:
            t = templates[int(rng.randint(len(templates)))]
            keep = int(rng.choice([8, 16, 24]))
            prompt = np.concatenate([t[:keep], prompt])[:32]
        # decode-heavy budgets: optimistic admission reserves only the
        # prefill's pages, so long decodes are what actually
        # oversubscribe the pool and exercise preemption
        max_new = int(rng.randint(8, 33))
        adapter = None
        allowed = None
        if lora:
            # extra draws ONLY on the lora leg (keyed off args.lora),
            # so the other legs' seeded schedules are unchanged:
            # ~2/3 of traffic is tenant traffic over 2 adapters, and
            # ~1/4 additionally carries a structured-decoding vocab
            # mask (half-vocab; greedy stays deterministic, so the
            # fault-free replay is still well-defined)
            adapter = [None, "a0", "a0", "a1", "a1",
                       "a2"][int(rng.randint(6))]
            if rng.random_sample() < 0.25:
                allowed = rng.random_sample(args.vocab) < 0.5
                allowed[int(rng.randint(args.vocab))] = True  # nonempty
        arrivals.append((step % max(1, args.steps - 5), prompt,
                         max_new, adapter, allowed))
    arrivals.sort(key=lambda a: a[0])
    # cancel ~10% of arrivals a few steps after they land; small
    # schedules can draw zero, so force one mid-window cancel — the
    # unwind/restart recovery paths must be exercised by every run
    cancels = {}    # step -> [arrival ordinal]
    n_cancels = 0
    for i in range(len(arrivals)):
        if rng.random_sample() < 0.1:
            cstep = arrivals[i][0] + int(rng.randint(1, 6))
            cancels.setdefault(cstep, []).append(i)
            n_cancels += 1
    if not n_cancels and arrivals:
        i = len(arrivals) // 2
        cancels.setdefault(arrivals[i][0] + 2, []).append(i)
    return arrivals, cancels


def run_schedule(model, args, chaotic: bool, tracer=None):
    """One full run; returns (results-by-ordinal, engine-or-router,
    monkey-or-monkeys, steps_run). With --dp R > 1 the engine is a
    fleet Router: every replica gets its own seeded background monkey,
    and at a SEEDED mid-run step replica 0's monkey turns into a
    persistent wedge (ChaosMonkey.wedge — every dispatch/fetch fails
    from then on); the Router must trip its breaker, drain the replica
    and redistribute, with migrated requests finishing token-identical
    to the fault-free fleet replay."""
    from paddle_tpu.inference import SamplingParams
    from paddle_tpu.utils.chaos import ChaosMonkey

    dp = getattr(args, "dp", 1)
    # the dp_proc leg (ISSUE 19): only the CHAOS run is a process
    # fleet — the fault-free replay runs inproc, so token identity
    # also proves the process transport changes no tokens
    proc = (dp > 1 and chaotic
            and getattr(args, "dp_transport", "inproc") == "process")
    if dp > 1:
        eng = build_fleet(model, args, tracer=tracer,
                          transport="process" if proc else "inproc")
        monkey = None
        if chaotic and proc:
            # worker-side monkeys are BUILT INSIDE each worker over
            # the chaos_attach verb (same seeds/probabilities as the
            # inproc leg; the config is replayed into a respawned
            # worker); parent-side monkeys drop/delay RPCs at the
            # transport boundary — the retry/backoff + reply-cache
            # exactly-once path, exercised deterministically
            for r, rep in enumerate(eng.replicas):
                rep.transport.chaos_attach(
                    seed=args.seed + 1 + r, p_alloc_oom=args.p_oom,
                    p_dispatch=args.p_dispatch,
                    p_collect=args.p_collect,
                    p_latency=args.p_latency)
            monkey = []
            for r, rep in enumerate(eng.replicas):
                pm = ChaosMonkey(
                    seed=args.seed + 101 + r,
                    p_rpc_drop=getattr(args, "p_rpc_drop", 0.0),
                    p_rpc_delay=getattr(args, "p_rpc_delay", 0.0))
                rep.transport.fault_hook = pm.transport_fault
                monkey.append(pm)
        elif chaotic:
            monkey = [ChaosMonkey(
                seed=args.seed + 1 + r, p_alloc_oom=args.p_oom,
                p_dispatch=args.p_dispatch, p_collect=args.p_collect,
                p_latency=args.p_latency).attach(rep.engine)
                for r, rep in enumerate(eng.replicas)]
        wedge_step = args.steps // 3
    else:
        eng = build_engine(model, args, tracer=tracer)
        monkey = None
        if chaotic:
            monkey = ChaosMonkey(
                seed=args.seed + 1, p_alloc_oom=args.p_oom,
                p_dispatch=args.p_dispatch, p_collect=args.p_collect,
                p_latency=args.p_latency).attach(eng)
    if chaotic and getattr(args, "seal_programs", False):
        # grid-compile + seal BEFORE any traffic (ISSUE 14): direct
        # program invocation, so the monkey (which hooks _device_call)
        # never fires and no scheduler state or PRNG key is touched —
        # the fault-free replay needs no matching warmup. From here
        # any retrace the fault schedule provokes is counted and
        # fails the leg.
        eng.warmup_programs()
        eng.seal_programs()
    arrivals, cancels = gen_workload(args)
    rid_of = {}
    next_arrival = 0
    steps_run = 0
    user_cancels = 0   # cancels that actually landed on a live request
    #                    (distinct from drain-migration aborts: the dp
    #                    wedge drain aborts victims too, so the
    #                    cancellation event must count USER cancels)

    def debug_check():
        if dp > 1:
            for rep in eng.replicas:
                if rep.transport.remote:
                    # the pool invariant holds INSIDE the worker; a
                    # dead/wedged worker has no pool left to check
                    if rep.state != "wedged" and rep.transport.alive():
                        try:
                            rep.transport.debug_check()
                        except Exception as e:  # noqa: BLE001
                            # a REAL pool violation is an ASSERTION
                            # inside the worker and must fail the leg;
                            # a worker dying/timing out mid-check is
                            # the supervisor's event, not a violation
                            if "AssertionError" in str(e):
                                raise

                else:
                    rep.engine.dec.cache.debug_check()
        else:
            eng.dec.cache.debug_check()

    def inject_step_events(step):
        nonlocal next_arrival
        while next_arrival < len(arrivals) \
                and arrivals[next_arrival][0] <= step:
            _, prompt, max_new, adapter, allowed = \
                arrivals[next_arrival]
            rid_of[next_arrival] = eng.add_request(
                prompt, SamplingParams(max_new_tokens=max_new,
                                       adapter_id=adapter,
                                       allowed_tokens=allowed))
            next_arrival += 1
        if chaotic:
            nonlocal user_cancels
            if dp > 1 and step == wedge_step:
                if proc:
                    # hard death instead of a wedge: the worker
                    # SIGKILLs itself mid-run — the Router must see
                    # pipe EOF, drain replica 0 from its JOURNAL,
                    # migrate token-identically and RESPAWN
                    eng.replicas[0].transport.inject_kill()
                else:
                    monkey[0].wedge()
            for ordinal in cancels.get(step, ()):
                rid = rid_of.get(ordinal)
                if rid is None:
                    continue
                if dp > 1:
                    if eng.cancel(rid):   # False on terminal — no-op
                        user_cancels += 1
                elif rid not in eng._done:
                    if eng.cancel(rid):
                        user_cancels += 1

    try:
        for step in range(args.steps):
            inject_step_events(step)
            eng.step()
            debug_check()
            steps_run += 1
        # drain (chaos stays attached: the tail is chaotic too;
        # schedule events keep firing so nothing lands silently past
        # the window)
        drain_cap = 50 * args.steps
        step = args.steps
        while eng.has_work and drain_cap > 0:
            inject_step_events(step)
            eng.step()
            debug_check()
            steps_run += 1
            step += 1
            drain_cap -= 1
        if eng.has_work:
            raise RuntimeError("engine failed to drain (livelock?)")
        results = {}
        for ordinal, rid in rid_of.items():
            req = eng.request(rid)
            results[ordinal] = (req.state, list(req.out_tokens),
                                req.error)
    except BaseException:
        # a crashing fleet run must not leak worker processes: the
        # harness exits red either way, but orphaned spawn children
        # would outlive it (ISSUE 19 shutdown contract)
        if dp > 1:
            try:
                eng.close()
            except Exception:       # noqa: BLE001 — best-effort
                pass
        raise
    return results, eng, monkey, steps_run, user_cancels


def build_parser() -> argparse.ArgumentParser:
    """The CLI surface, exposed for tests: parse_args([]) yields a
    fully-populated defaults Namespace that tracks new knobs
    automatically (a hand-built Namespace goes stale the moment
    run_schedule grows an option)."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    # default pool: 14 blocks; the lora leg defaults to 24 — the two
    # 3-page adapters permanently displace KV capacity (that is the
    # unified-pool design), and at 14 the displaced pool tips the
    # oldest-runner self-preemption cycle into a genuine no-progress
    # regime (nothing to do with faults: the fault-free replay wedges
    # too). 24 keeps real eviction/refault pressure without starving
    # the oldest request of the headroom it needs to ever finish.
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--retries", type=int, default=1)
    ap.add_argument("--p-oom", type=float, default=0.05)
    ap.add_argument("--p-dispatch", type=float, default=0.04)
    ap.add_argument("--p-collect", type=float, default=0.03)
    ap.add_argument("--p-latency", type=float, default=0.02)
    ap.add_argument("--ragged", action="store_true",
                    help="exercise the ragged unified prefill+decode "
                         "path (ISSUE 5): both the chaos and the "
                         "fault-free replay run with ragged=True")
    ap.add_argument("--kv-quant", choices=("int8",), default=None,
                    help="run BOTH legs on the quantized KV pool "
                         "(ISSUE 13): int8 planes + sidecar scales — "
                         "the whole fault schedule (OOM-preemption, "
                         "rollback, eviction, cancellation) must hold "
                         "debug_check on the int8 layout and stay "
                         "token-identical vs the fault-free replay "
                         "(both replays quantized, so identity is "
                         "well-defined)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (ISSUE 8): both runs "
                         "serve on the sharded shard_map engine — "
                         "OOM-preemption, injected dispatch faults and "
                         "cancellation must stay token-identical under "
                         "sharding (implies the ragged path)")
    ap.add_argument("--lora", action="store_true",
                    help="exercise multi-tenant many-LoRA serving "
                         "(ISSUE 10): both runs attach a seeded "
                         "3-adapter registry, ~2/3 of arrivals carry "
                         "an adapter id (some with allowed_tokens "
                         "masks), and the whole fault schedule — "
                         "adapter eviction under pool pressure, "
                         "OOM-preemption with adapter refault on "
                         "resume, cancellation — must stay "
                         "token-identical vs the fault-free replay "
                         "(implies ragged)")
    ap.add_argument("--multi-step", type=int, default=1,
                    dest="multi_step",
                    help="multi-step fused decode depth (ISSUE 16): "
                         "both runs fuse k serving steps into one "
                         "device program in the pure-decode regime — "
                         "the whole fault schedule (OOM-preemption "
                         "neutralizing whole windows, injected "
                         "dispatch faults, mid-window cancellation "
                         "taking effect at the next k-boundary, "
                         "debug_check after every boundary) must stay "
                         "token-identical vs the fault-free replay "
                         "(implies ragged)")
    ap.add_argument("--spec", action="store_true",
                    help="exercise speculative decoding (ISSUE 9): "
                         "both runs serve with "
                         "spec_decode=SpecConfig(draft_len=4) — n-gram "
                         "drafts ride the verify program through the "
                         "whole fault schedule (OOM-preemption "
                         "mid-window, injected dispatch/collect "
                         "faults, cancellation) and surviving outputs "
                         "must stay token-identical (implies ragged)")
    ap.add_argument("--dp", type=int, default=1,
                    help="fleet replica count (ISSUE 11): both runs "
                         "serve through a dp-replica prefix-affinity "
                         "Router; the chaos run additionally WEDGES "
                         "replica 0 at a seeded mid-run step "
                         "(persistent dispatch+collect faults) — the "
                         "router must trip its circuit breaker, drain "
                         "the replica and redistribute its queue, and "
                         "every surviving AND migrated request must "
                         "stay token-identical vs the fault-free "
                         "fleet replay")
    ap.add_argument("--dp-transport", choices=("inproc", "process"),
                    default="inproc", dest="dp_transport",
                    help="fleet transport for the CHAOS run (ISSUE "
                         "19): 'process' spawns each replica's engine "
                         "in its own worker process and replaces the "
                         "wedge with a mid-run SIGKILL of replica 0's "
                         "worker — the Router must fail fast on pipe "
                         "EOF, drain from its journal, migrate "
                         "token-identically, RESPAWN the worker "
                         "(warmup+seal replayed) and re-admit it via "
                         "probation; parent-side monkeys additionally "
                         "drop/delay RPCs to exercise bounded retry "
                         "with exactly-once replies. The fault-free "
                         "replay always runs inproc, so token "
                         "identity also proves the transport is "
                         "token-neutral")
    ap.add_argument("--p-rpc-drop", type=float, default=None,
                    dest="p_rpc_drop",
                    help="per-RPC-stage drop probability for the "
                         "process-fleet parent monkeys (default 0.03 "
                         "with --dp-transport process, else 0)")
    ap.add_argument("--p-rpc-delay", type=float, default=0.02,
                    dest="p_rpc_delay",
                    help="per-RPC-stage seeded delay probability for "
                         "the process-fleet parent monkeys")
    ap.add_argument("--trace-out", default=None,
                    help="run the chaos leg with serving telemetry ON "
                         "(ISSUE 12) and write the flight-recorder "
                         "Perfetto export here — on success, mismatch "
                         "OR crash (the replay stays untraced, so "
                         "token identity also proves tracing is "
                         "schedule-neutral)")
    ap.add_argument("--seal-programs", action="store_true",
                    help="grid-compile + SEAL the chaos engine's "
                         "program set before traffic (ISSUE 14): any "
                         "mid-run XLA retrace then fails the leg via "
                         "unexpected_recompiles — the runtime FC2xx. "
                         "Bounds ragged_idle_cap (default 8) on both "
                         "runs so the reachable grid is closed")
    ap.add_argument("--ragged-idle-cap", type=int, default=None,
                    help="idle-drain width bound for ragged engines "
                         "(both runs; defaults to 8 under "
                         "--seal-programs, engine default otherwise)")
    ap.add_argument("--require-events", action="store_true",
                    help="fail unless >=1 preemption, >=1 injected "
                         "dispatch fault and >=1 cancellation/abort "
                         "actually happened (with --spec, also >=1 "
                         "draft rejection; with --dp, the preemption "
                         "requirement is replaced by >=1 replica "
                         "failover and >=1 migrated-request "
                         "completion)")
    return ap


def main() -> int:
    args = build_parser().parse_args()
    if args.num_blocks is None:
        args.num_blocks = 24 if args.lora else 14
    if args.ragged_idle_cap is None and args.seal_programs:
        args.ragged_idle_cap = 8
    if args.p_rpc_drop is None:
        args.p_rpc_drop = 0.03 if args.dp_transport == "process" \
            else 0.0
    args.vocab = None

    if args.tp > 1:
        # the tp mesh needs the multi-device CPU backend before
        # anything initializes jax (the conftest dance, standalone)
        from tools.flightcheck.comm_audit import ensure_devices
        ensure_devices(max(8, args.tp))

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    paddle.seed(0)
    cfg = llama_tiny()
    args.vocab = cfg.vocab_size
    model = LlamaForCausalLM(cfg)
    model.eval()

    base_results, base_eng, _, _, _ = run_schedule(model, args,
                                                   chaotic=False)
    tracer = None
    if args.trace_out:
        from paddle_tpu.utils.telemetry import Tracer
        tracer = Tracer()
    try:
        chaos_results, eng, monkey, steps_run, user_cancels = \
            run_schedule(model, args, chaotic=True, tracer=tracer)
    finally:
        # the flight recorder is the post-mortem: it must land next to
        # the log even (especially) when the chaos run crashed
        if tracer is not None:
            tracer.export(args.trace_out)

    mismatches = []
    done = faulted = 0
    for ordinal, (state, toks, err) in sorted(chaos_results.items()):
        if state == "done":
            done += 1
            bstate, btoks, _ = base_results[ordinal]
            if toks != btoks:
                mismatches.append(
                    {"ordinal": ordinal, "chaos": toks, "base": btoks})
        else:
            faulted += 1
    if args.dp > 1:
        from collections import Counter
        proc = args.dp_transport == "process"
        full = eng.stats()
        fleet = full["fleet"]
        injected = Counter()
        if proc:
            # worker-side injections live in the WORKERS' monkeys:
            # harvest over the chaos_counts verb from every replica
            # still answering (a SIGKILL'd generation's counts died
            # with it — the parent-side supervisor counters below are
            # the record of the death itself); the parent monkeys
            # contribute the RPC drop/delay counts
            for rep in eng.replicas:
                if rep.transport.alive() and rep.state != "wedged":
                    try:
                        injected.update(rep.transport.chaos_counts())
                    except Exception:   # noqa: BLE001 — best-effort
                        pass
            for m in monkey:
                injected.update(m.counts)
        else:
            for m in monkey:
                injected.update(m.counts)
        summary = {
            "dp": args.dp,
            "transport": args.dp_transport,
            "ragged": bool(args.ragged),
            "kv_quant": full["replicas"][0].get("kv_quant"),
            "steps": steps_run,
            "requests": len(chaos_results),
            "failovers": fleet["failovers"],
            "migrated_requests": fleet["migrated_requests"],
            "migrated_done": fleet["migrated_done"],
            "affinity_hits": fleet["affinity_hits"],
            "spills": fleet["spills"],
            "preemptions": fleet["preemptions"],
            "aborted": fleet["aborted"],
            "failed": fleet["failed"],
            "retries": fleet["retries"],
            "dispatch_exhaustions": fleet["dispatch_exhaustions"],
            "wedged_replicas": fleet["wedged_replicas"],
            "user_cancels": user_cancels,
            "injected": dict(injected),
            "program_compiles": fleet["program_compiles"],
            "unexpected_recompiles": fleet["unexpected_recompiles"],
            # -- process fleet (ISSUE 19) -----------------------------
            "worker_exits": fleet["worker_exits"],
            "worker_restarts": fleet["worker_restarts"],
            "heartbeat_misses": fleet["heartbeat_misses"],
            "rpc_retries": fleet["rpc_retries"],
            "journal_requests": fleet["journal_requests"],
        }
        summary["done_identical"] = done - len(mismatches)
        summary["mismatches"] = len(mismatches)
        summary["faulted"] = faulted
        ok = not mismatches
        if args.seal_programs and fleet["unexpected_recompiles"]:
            # sealed-set violation (ISSUE 14): some replica's fault
            # schedule provoked an XLA retrace — always fatal when
            # sealing was requested, exactly like a token mismatch
            print(f"UNEXPECTED RECOMPILES: "
                  f"{fleet['unexpected_recompiles']} after seal",
                  file=sys.stderr)
            ok = False
        if args.require_events:
            missing = []
            if fleet["failovers"] < 1:
                missing.append("replica_failover")
            if fleet["migrated_done"] < 1:
                missing.append("migrated_request_completion")
            if injected.get("dispatch_faults", 0) < 1:
                missing.append("dispatch_fault")
            # USER cancels specifically: the wedge drain aborts its
            # victims too, so fleet["aborted"] >= 1 is guaranteed by
            # failover alone and would mask a dead cancel path
            if user_cancels < 1:
                missing.append("cancellation")
            if proc:
                # the dp_proc leg must actually exercise the death +
                # supervisor + retry machinery, not just route RPCs
                if fleet["worker_exits"] < 1:
                    missing.append("worker_exit")
                if fleet["worker_restarts"] < 1:
                    missing.append("worker_respawn")
                if fleet["rpc_retries"] < 1:
                    missing.append("rpc_retry")
            if missing:
                summary["missing_events"] = missing
                ok = False
        summary["ok"] = ok
        if args.trace_out:
            summary["trace"] = args.trace_out
        print(json.dumps(summary))
        for m in mismatches[:4]:
            print(f"MISMATCH ordinal {m['ordinal']}: "
                  f"chaos={m['chaos']} base={m['base']}",
                  file=sys.stderr)
        # shutdown contract (ISSUE 19): no leaked worker processes —
        # idempotent, and a no-op for the inproc legs
        eng.close()
        base_eng.close()
        return 0 if ok else 1

    st = eng.stats()
    summary = {
        "ragged": args.ragged or args.tp > 1 or args.spec or args.lora
        or args.multi_step > 1,
        "tp": args.tp,
        "spec": bool(args.spec),
        "lora": bool(args.lora),
        "multi_step": args.multi_step,
        "multi_step_windows": st["multi_step_windows"],
        "ms_frozen_token_waste": st["ms_frozen_token_waste"],
        "kv_quant": st["kv_quant"],
        "kv_bytes_per_token": st["kv_bytes_per_token"],
        "active_adapters": st["active_adapters"],
        "adapter_cache_hits": st["adapter_cache_hits"],
        "adapter_cache_misses": st["adapter_cache_misses"],
        "adapter_cache_evictions": st["adapter_cache_evictions"],
        "masked_decode_columns": st["masked_decode_columns"],
        "drafted_tokens": st["drafted_tokens"],
        "accepted_draft_tokens": st["accepted_draft_tokens"],
        "spec_rollbacks": st["spec_rollbacks"],
        "steps": steps_run,
        "requests": len(chaos_results),
        "done_identical": done - len(mismatches),
        "mismatches": len(mismatches),
        "faulted": faulted,
        "preemptions": st["preemptions"],
        "recompute_tokens": st["recompute_tokens"],
        "retries": st["retries"],
        "aborted": st["aborted"],
        "failed": st["failed"],
        "injected": dict(monkey.counts),
        "program_compiles": st["program_compiles"],
        "unexpected_recompiles": st["unexpected_recompiles"],
    }
    ok = not mismatches
    if args.seal_programs and st["unexpected_recompiles"]:
        # sealed-set violation (ISSUE 14): the fault schedule provoked
        # an XLA retrace past warmup — always fatal when sealing was
        # requested, exactly like a token mismatch
        print(f"UNEXPECTED RECOMPILES: {st['unexpected_recompiles']} "
              f"after seal", file=sys.stderr)
        ok = False
    if args.require_events:
        missing = []
        if st["preemptions"] < 1:
            missing.append("oom_preemption")
        if monkey.counts.get("dispatch_faults", 0) < 1:
            missing.append("dispatch_fault")
        if st["aborted"] < 1:
            missing.append("cancellation")
        if args.spec and st["spec_rollbacks"] < 1:
            # the spec leg must actually exercise the rejected-tail
            # rollback path, not just ride accepted drafts
            missing.append("draft_rejection")
        if args.multi_step > 1 and st["multi_step_windows"] < 1:
            # the multi-step leg must actually dispatch fused windows,
            # not spend the whole schedule in the prefill regime
            missing.append("fused_window")
        if args.lora:
            # the lora leg must actually exercise adapter paging, not
            # just ride two permanently-resident adapters: at least
            # one previously-resident adapter must have been found
            # EVICTED at re-acquire (refaulted from host) under the
            # pool pressure the tight num_blocks creates
            if st["adapter_cache_evictions"] < 1:
                missing.append("adapter_eviction")
            if st["masked_decode_columns"] < 1:
                missing.append("masked_decode")
        if missing:
            summary["missing_events"] = missing
            ok = False
    summary["ok"] = ok
    if args.trace_out:
        summary["trace"] = args.trace_out
    print(json.dumps(summary))
    if mismatches:
        for m in mismatches[:4]:
            print(f"MISMATCH ordinal {m['ordinal']}: chaos={m['chaos']}"
                  f" base={m['base']}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
