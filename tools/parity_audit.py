#!/usr/bin/env python
"""API parity audit: diff each namespace's public surface against the
reference source tree and emit PARITY.md (VERDICT r2 #10).

The reference can't be imported (its C++ core isn't built here), so its
public names are collected statically: the namespace __init__.py's
__all__ (or the name-list variable noted in NAMESPACES) parsed via ast.
Ours is the live import. Run from the repo root:

    python tools/parity_audit.py           # writes PARITY.md
    python tools/parity_audit.py --check   # exit 1 if % regressed vs
                                           # the floors in this file

The floors below are ratchets: raise them as gaps close; --check keeps
CI honest about regressions without demanding 100% of namespaces whose
gaps are documented descopes (COVERAGE.md).
"""
from __future__ import annotations

import ast
import importlib
import os
import sys

# `python tools/parity_audit.py` puts tools/ (not the repo root) on
# sys.path — make paddle_tpu importable regardless of invocation
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REF = "/root/reference/python/paddle"

# namespace → (reference file, name-list variable). "__all__" is the
# default; paddle.tensor exports through tensor_method_func + __all__.
NAMESPACES = {
    "paddle": ("__init__.py", "__all__"),
    "paddle.tensor": ("tensor/__init__.py", "tensor_method_func"),
    "paddle.nn": ("nn/__init__.py", "__all__"),
    "paddle.nn.functional": ("nn/functional/__init__.py", "__all__"),
    "paddle.nn.initializer": ("nn/initializer/__init__.py", "__all__"),
    "paddle.optimizer": ("optimizer/__init__.py", "__all__"),
    "paddle.optimizer.lr": ("optimizer/lr.py", "__all__"),
    "paddle.io": ("io/__init__.py", "__all__"),
    "paddle.amp": ("amp/__init__.py", "__all__"),
    "paddle.static": ("static/__init__.py", "__all__"),
    "paddle.jit": ("jit/__init__.py", "__all__"),
    "paddle.distributed": ("distributed/__init__.py", "__all__"),
    "paddle.distribution": ("distribution/__init__.py", "__all__"),
    "paddle.vision.transforms": ("vision/transforms/__init__.py",
                                 "__all__"),
    "paddle.vision.models": ("vision/models/__init__.py", "__all__"),
    "paddle.vision.ops": ("vision/ops.py", "__all__"),
    "paddle.audio.features": ("audio/features/__init__.py", "__all__"),
    "paddle.audio.functional": ("audio/functional/__init__.py",
                                "__all__"),
    "paddle.text": ("text/__init__.py", "__all__"),
    "paddle.sparse": ("sparse/__init__.py", "__all__"),
    "paddle.geometric": ("geometric/__init__.py", "__all__"),
    "paddle.fft": ("fft.py", "__all__"),
    "paddle.signal": ("signal.py", "__all__"),
    "paddle.linalg": ("linalg.py", "__all__"),
    "paddle.metric": ("metric/__init__.py", "__all__"),
    "paddle.incubate.nn.functional": ("incubate/nn/functional/"
                                      "__init__.py", "__all__"),
    "paddle.quantization": ("quantization/__init__.py", "__all__"),
    "paddle.nn.quant": ("nn/quant/__init__.py", "__all__"),
    "paddle.onnx": ("onnx/__init__.py", "__all__"),
    "paddle.cost_model": ("cost_model/__init__.py", "__all__"),
    "paddle.inference": ("inference/__init__.py", "__all__"),
}

# ratchet floors for --check (percent present). Raise, never lower.
FLOORS = {"_overall": 99.0}


def ref_names(rel_path: str, var: str) -> list[str]:
    path = os.path.join(REF, rel_path)
    tree = ast.parse(open(path).read())
    out: list[str] = []
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [getattr(t, "id", None) for t in node.targets]
        elif isinstance(node, ast.AugAssign):
            targets = [getattr(node.target, "id", None)]
        if var not in targets:
            continue
        val = node.value
        if isinstance(val, (ast.List, ast.Tuple)):
            out.extend(e.value for e in val.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str))
    return sorted(set(out))


def our_namespace(name: str):
    ours = name.replace("paddle", "paddle_tpu", 1)
    try:
        return importlib.import_module(ours)
    except ImportError:
        return None


def audit():
    rows = []
    total_ref = total_have = 0
    for ns, (rel, var) in NAMESPACES.items():
        names = ref_names(rel, var)
        # private names aren't parity surface
        names = [n for n in names if not n.startswith("_")]
        mod = our_namespace(ns)
        if mod is None:
            have, missing = [], names
        else:
            have = [n for n in names if hasattr(mod, n)]
            missing = [n for n in names if not hasattr(mod, n)]
        pct = 100.0 * len(have) / len(names) if names else 100.0
        rows.append((ns, len(names), len(have), pct, missing))
        total_ref += len(names)
        total_have += len(have)
    overall = 100.0 * total_have / total_ref if total_ref else 100.0
    return rows, overall


def write_md(rows, overall, path="PARITY.md"):
    lines = [
        "# API parity vs the reference (generated by "
        "tools/parity_audit.py — do not hand-edit)",
        "",
        f"**Overall: {overall:.1f}%** of the reference's public names "
        "resolve in paddle_tpu (name-level parity; behavior parity is "
        "the test suite's job). Deliberate descopes are documented in "
        "COVERAGE.md.",
        "",
        "| namespace | ref names | present | % | missing (first 12) |",
        "|---|---|---|---|---|",
    ]
    for ns, nref, nhave, pct, missing in sorted(rows,
                                                key=lambda r: r[3]):
        miss = ", ".join(missing[:12])
        if len(missing) > 12:
            miss += f", … (+{len(missing) - 12})"
        lines.append(f"| {ns} | {nref} | {nhave} | {pct:.1f} | "
                     f"{miss} |")
    open(path, "w").write("\n".join(lines) + "\n")


def main():
    rows, overall = audit()
    write_md(rows, overall)
    print(f"PARITY.md written — overall {overall:.1f}% "
          f"({sum(r[2] for r in rows)}/{sum(r[1] for r in rows)})")
    worst = sorted(rows, key=lambda r: r[3])[:5]
    for ns, nref, nhave, pct, _ in worst:
        print(f"  worst: {ns}: {pct:.1f}% ({nhave}/{nref})")
    if "--check" in sys.argv:
        ok = overall >= FLOORS["_overall"]
        for ns, _, _, pct, _ in rows:
            floor = FLOORS.get(ns)
            if floor is not None and pct < floor:
                print(f"REGRESSION: {ns} {pct:.1f}% < floor {floor}")
                ok = False
        if not ok:
            print(f"REGRESSION: overall {overall:.1f}% < "
                  f"{FLOORS['_overall']}")
            sys.exit(1)
    return overall


if __name__ == "__main__":
    main()
