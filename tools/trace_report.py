#!/usr/bin/env python
"""Summarize a serving telemetry trace (ISSUE 12).

Reads the Chrome-trace/Perfetto JSON written by
``paddle_tpu.utils.telemetry.Tracer.export`` and prints the post-mortem
a red gate run (or a bench artifact) needs without opening the UI:

- per-phase latency breakdown: count / total / mean / p50 / p99 of
  every span name (queued, prefill, splice_wait, decode, ...);
- per-replica occupancy: span-busy seconds per replica track over the
  trace wall clock (an approximation — overlapping spans of different
  requests double-count busy time, so >100% means real concurrency);
- dispatch mix per replica (ragged/decode/prefill/spec counts);
- top preempted / migrated requests, with req ids and tenant
  attributes off the request-begin records;
- terminal-state counts and the event tally (retries, injected
  faults, breaker strikes, kv churn);
- compile-span table (ISSUE 14): per program family, compile count +
  total/max compile wall and the XLA flops / bytes-accessed numbers
  when CompileWatch's analyze mode recorded them, plus the
  unexpected-recompile verdict;
- counter-track summaries: min/mean/max/last of every ``ph:"C"``
  resource timeline (running slots, free blocks, queue depth, ...)
  per replica track;
- SLO section: ``slo_violation`` events plus the burn-rate / headroom
  gauges riding the exported metrics snapshot;
- dispatch amortization (ISSUE 16): tokens per dispatch grouped by
  (kind, fused-window depth k) off the dispatch events' ``k`` /
  ``decode_toks`` args, plus sampled device-execute totals per
  program family (the ragged_ms* families are the k>1 windows);
- worker lifecycle (ISSUE 19): process-fleet supervision off the
  fleet track — worker exits grouped by reason, respawn count and
  wall-clock, heartbeat misses, and migrations.

Pure host tool: no jax, no paddle_tpu import — runs anywhere the JSON
does.

    python tools/trace_report.py trace.json
    python tools/trace_report.py trace.json --json   # machine-readable
    python tools/trace_report.py trace.json --top 10
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict


def _pct(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    i = (len(xs) - 1) * p
    lo, hi = int(i), min(int(i) + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (i - lo)


def _pid_name(pid):
    # keep in sync with telemetry.FLEET_PID (no import: pure host tool)
    return "fleet" if pid == 1000 else f"replica{pid}"


def analyze(doc: dict, top: int = 5) -> dict:
    evts = doc.get("traceEvents", [])
    spans = [e for e in evts if e.get("ph") == "X"]
    insts = [e for e in evts if e.get("ph") == "i"]
    counters = [e for e in evts if e.get("ph") == "C"]
    begins = {e.get("id"): e for e in evts if e.get("ph") == "b"}
    ends = {e.get("id"): e for e in evts if e.get("ph") == "e"}

    # -- per-phase latency breakdown ------------------------------------
    # compile spans get their own table below — they are program
    # lifecycle, not request phases
    by_phase: dict = defaultdict(list)
    for s in spans:
        if s["name"] == "compile":
            continue
        by_phase[s["name"]].append(s.get("dur", 0.0) / 1e6)
    phases = {}
    for name, durs in sorted(by_phase.items()):
        phases[name] = {
            "count": len(durs),
            "total_s": round(sum(durs), 4),
            "mean_s": round(sum(durs) / len(durs), 5),
            "p50_s": round(_pct(durs, 0.50), 5),
            "p99_s": round(_pct(durs, 0.99), 5),
        }

    # -- per-replica occupancy + dispatch mix ---------------------------
    ts_all = [e["ts"] for e in evts if e.get("ph") in ("X", "i", "b", "e")]
    wall_s = ((max(ts_all) - min(ts_all)) / 1e6) if ts_all else 0.0
    busy: Counter = Counter()
    for s in spans:
        # waiting phases are not device work: a queue-backed-up idle
        # replica must not read as saturated. Compile spans are
        # warmup/one-off cost with their own table — a grid-warmed
        # trace must not read as a saturated replica either.
        if s["name"] in ("queued", "splice_wait", "compile"):
            continue
        busy[s["pid"]] += s.get("dur", 0.0) / 1e6
    dispatch_mix: dict = defaultdict(Counter)
    for e in insts:
        if e["name"] == "dispatch":
            dispatch_mix[e["pid"]][e.get("args", {}).get("kind", "?")] \
                += 1
    replicas = {}
    for pid in sorted(set(busy) | set(dispatch_mix)):
        replicas[_pid_name(pid)] = {
            "busy_s": round(busy.get(pid, 0.0), 4),
            "occupancy": (round(busy.get(pid, 0.0) / wall_s, 4)
                          if wall_s else None),
            "dispatches": dict(dispatch_mix.get(pid, {})),
        }

    # -- per-request robustness: preempt / migrate counts ---------------
    preempts: Counter = Counter()
    migrations: Counter = Counter()
    for e in insts:
        tid = e.get("tid")
        if e["name"] == "preempt" and tid:
            preempts[tid] += 1
        elif e["name"] == "migrate" and tid:
            migrations[tid] += 1

    def _req_label(tid):
        b = begins.get(str(tid)) or begins.get(tid)
        if b is None:
            return {"trace": tid}
        a = b.get("args", {})
        out = {"trace": tid, "req_id": a.get("req_id")}
        if "tenant" in a:
            out["tenant"] = a["tenant"]
        return out

    top_preempted = [dict(_req_label(t), preemptions=n)
                     for t, n in preempts.most_common(top)]
    top_migrated = [dict(_req_label(t), migrations=n)
                    for t, n in migrations.most_common(top)]

    # -- terminal states + event tally ----------------------------------
    states: Counter = Counter()
    for e in ends.values():
        states[e.get("args", {}).get("state", "?")] += 1
    events: Counter = Counter(e["name"] for e in insts)

    # -- compile-span table (ISSUE 14) ----------------------------------
    # one row per program family: how often it compiled, the wall it
    # cost, and the XLA cost/memory analysis when the watch recorded
    # it (analyze mode). unexpected counts compiles observed AFTER
    # seal_programs — the runtime FC2xx; any non-zero row is the
    # retrace the gate legs assert against.
    fam_rows: dict = defaultdict(lambda: {
        "count": 0, "total_wall_s": 0.0, "max_wall_s": 0.0,
        "unexpected": 0})
    for s in spans:
        if s["name"] != "compile":
            continue
        a = s.get("args", {})
        row = fam_rows[a.get("family", "?")]
        w = s.get("dur", 0.0) / 1e6
        row["count"] += 1
        row["total_wall_s"] += w
        row["max_wall_s"] = max(row["max_wall_s"], w)
        if a.get("sealed"):
            row["unexpected"] += 1
        for k in ("flops", "bytes_accessed", "temp_bytes",
                  "output_bytes", "argument_bytes"):
            if k in a:
                row[k] = a[k]
    compiles = {}
    for fam, row in sorted(fam_rows.items()):
        row["total_wall_s"] = round(row["total_wall_s"], 4)
        row["max_wall_s"] = round(row["max_wall_s"], 4)
        compiles[fam] = row
    unexpected_recompiles = (
        events.get("unexpected_recompile", 0)
        or sum(r["unexpected"] for r in compiles.values()))

    # -- counter-track summaries (ISSUE 14) -----------------------------
    # per (replica track, counter name): sample count + min/mean/max
    # and the final value — the text view of the Perfetto timelines
    track_vals: dict = defaultdict(list)
    for c in counters:
        v = c.get("args", {}).get("value")
        if v is not None:
            track_vals[(c["pid"], c["name"])].append(float(v))
    tracks: dict = {}
    for (pid, name), vals in sorted(track_vals.items()):
        tracks.setdefault(_pid_name(pid), {})[name] = {
            "n": len(vals),
            "min": round(min(vals), 4),
            "mean": round(sum(vals) / len(vals), 4),
            "max": round(max(vals), 4),
            "last": round(vals[-1], 4),
        }

    # -- dispatch amortization (ISSUE 16) -------------------------------
    # ragged dispatch events carry k (fused-window depth) and
    # decode_toks (decode tokens the window delivers); grouping by
    # (kind, k) shows the tokens-per-dispatch amortization the
    # multi-step refactor buys, and the per-family execute totals from
    # the sampled attribution events split the device wall by program
    # family (the ragged_ms* families are the k>1 windows)
    amort_rows: dict = defaultdict(
        lambda: {"dispatches": 0, "decode_toks": 0})
    for e in insts:
        if e["name"] != "dispatch":
            continue
        a = e.get("args", {})
        row = amort_rows[(a.get("kind", "?"), int(a.get("k", 1)))]
        row["dispatches"] += 1
        row["decode_toks"] += int(a.get("decode_toks", 0))
    amort: dict = {}
    for (kind, kk), row in sorted(amort_rows.items()):
        amort[f"{kind} k={kk}"] = {
            "dispatches": row["dispatches"],
            "decode_toks": row["decode_toks"],
            "toks_per_dispatch": round(
                row["decode_toks"] / row["dispatches"], 2),
        }
    exec_by_family: dict = defaultdict(
        lambda: {"samples": 0, "execute_s": 0.0})
    for e in insts:
        if e["name"] == "profile_sample":
            a = e.get("args", {})
            r = exec_by_family[a.get("family", "?")]
            r["samples"] += 1
            r["execute_s"] += float(a.get("execute_s", 0.0))
    execute = {fam: {"samples": r["samples"],
                     "execute_s": round(r["execute_s"], 4)}
               for fam, r in sorted(exec_by_family.items())}
    amortization = ({"dispatch": amort, "execute_by_family": execute}
                    if amort or execute else None)

    # -- SLO section (ISSUE 14) -----------------------------------------
    # violation events carry (policy, headroom at detection); the
    # exported metrics snapshot carries the latest burn-rate /
    # headroom gauges under the slo* namespaces
    slo_events = [dict(e.get("args", {}))
                  for e in insts if e["name"] == "slo_violation"]
    slo_gauges = {
        k: v for k, v in sorted(
            (doc.get("metrics", {}).get("gauges") or {}).items())
        if k.startswith("slo") or ".slo." in k}
    slo = ({"violations": slo_events, "gauges": slo_gauges}
           if (slo_events or slo_gauges) else None)

    # -- worker lifecycle (ISSUE 19) ------------------------------------
    # process-fleet supervision events off the fleet track: worker
    # exits grouped by reason (process_exit / heartbeat / ...),
    # respawn count + wall-clock each respawn paid (spawn + warmup
    # replay + re-seal), heartbeat misses, and migrations — the
    # crash-isolation story of a run at a glance
    w_exits = [dict(e.get("args", {}))
               for e in insts if e["name"] == "worker_exit"]
    w_spawns = [dict(e.get("args", {}))
                for e in insts if e["name"] == "worker_respawn"]
    hb_misses = sum(1 for e in insts if e["name"] == "heartbeat_miss")
    workers = None
    if w_exits or w_spawns or hb_misses:
        walls = [float(r.get("wall_s", 0.0)) for r in w_spawns]
        workers = {
            "exits": len(w_exits),
            "exits_by_reason": dict(Counter(
                x.get("reason", "?") for x in w_exits)),
            "respawns": len(w_spawns),
            "respawn_failed": sum(
                1 for e in insts
                if e["name"] == "worker_respawn_failed"),
            "respawn_wall_s": {
                "max": round(max(walls), 3),
                "total": round(sum(walls), 3),
            } if walls else None,
            "heartbeat_misses": hb_misses,
            "migrations": sum(
                1 for e in insts if e["name"] == "migrate"),
        }

    return {
        "wall_s": round(wall_s, 4),
        "records": len(evts),
        "dropped_records": doc.get("otherData", {}).get(
            "dropped_records", 0),
        "requests": {"begun": len(begins), "ended": len(ends),
                     "states": dict(states)},
        "phases": phases,
        "replicas": replicas,
        "top_preempted": top_preempted,
        "top_migrated": top_migrated,
        "events": dict(events),
        "compiles": compiles,
        "unexpected_recompiles": unexpected_recompiles,
        "tracks": tracks,
        "amortization": amortization,
        "slo": slo,
        "workers": workers,
    }


def format_report(rep: dict) -> str:
    lines = [f"trace: {rep['records']} records over {rep['wall_s']}s "
             f"wall ({rep['dropped_records']} dropped from the ring)"]
    rq = rep["requests"]
    lines.append(f"requests: {rq['begun']} begun, {rq['ended']} ended "
                 f"{rq['states']}")
    lines.append("per-phase latency:")
    for name, p in rep["phases"].items():
        lines.append(
            f"  {name:12s} n={p['count']:<5d} total={p['total_s']:<9g} "
            f"mean={p['mean_s']:<9g} p50={p['p50_s']:<9g} "
            f"p99={p['p99_s']:g}")
    lines.append("per-replica occupancy:")
    for name, r in rep["replicas"].items():
        occ = (f"{r['occupancy'] * 100:.1f}%"
               if r["occupancy"] is not None else "n/a")
        lines.append(f"  {name:10s} busy={r['busy_s']}s ({occ}) "
                     f"dispatches={r['dispatches']}")
    if rep["top_preempted"]:
        lines.append(f"top preempted: {rep['top_preempted']}")
    if rep["top_migrated"]:
        lines.append(f"top migrated: {rep['top_migrated']}")
    if rep.get("compiles"):
        verdict = rep.get("unexpected_recompiles", 0)
        lines.append(f"compiles (unexpected={verdict}):")
        for fam, r in rep["compiles"].items():
            extra = "".join(
                f" {k}={r[k]:g}" for k in ("flops", "bytes_accessed")
                if k in r)
            flag = (f" UNEXPECTED={r['unexpected']}"
                    if r["unexpected"] else "")
            lines.append(
                f"  {fam:18s} n={r['count']:<4d} "
                f"total={r['total_wall_s']:<9g} "
                f"max={r['max_wall_s']:g}{extra}{flag}")
        # XLA memory_analysis per family (CompileWatch analyze=True):
        # argument/peak-temp/output bytes of the last compile observed
        mem_fams = {fam: r for fam, r in rep["compiles"].items()
                    if any(k in r for k in (
                        "argument_bytes", "temp_bytes", "output_bytes"))}
        if mem_fams:
            lines.append("memory by family (XLA memory_analysis):")
            for fam, r in mem_fams.items():
                parts = "".join(
                    f" {label}={r[k]:g}B"
                    for k, label in (("argument_bytes", "args"),
                                     ("temp_bytes", "peak-temp"),
                                     ("output_bytes", "out"))
                    if k in r)
                lines.append(f"  {fam:18s}{parts}")
    if rep.get("tracks"):
        lines.append("counter tracks:")
        for rname, tr in rep["tracks"].items():
            for name, t in tr.items():
                lines.append(
                    f"  {rname}/{name:18s} n={t['n']:<5d} "
                    f"min={t['min']:<8g} mean={t['mean']:<8g} "
                    f"max={t['max']:<8g} last={t['last']:g}")
    if rep.get("amortization"):
        am = rep["amortization"]
        if am["dispatch"]:
            lines.append("dispatch amortization:")
            for key, r in am["dispatch"].items():
                lines.append(
                    f"  {key:22s} dispatches={r['dispatches']:<5d} "
                    f"decode_toks={r['decode_toks']:<7d} "
                    f"toks/dispatch={r['toks_per_dispatch']:g}")
        if am["execute_by_family"]:
            lines.append("device execute by family (sampled):")
            for fam, r in am["execute_by_family"].items():
                lines.append(
                    f"  {fam:18s} samples={r['samples']:<5d} "
                    f"execute={r['execute_s']:g}s")
    if rep.get("slo"):
        slo = rep["slo"]
        lines.append(f"slo: {len(slo['violations'])} violation "
                     f"event(s)")
        for v in slo["violations"]:
            lines.append(f"  VIOLATION {v}")
        for k, v in slo["gauges"].items():
            lines.append(f"  {k} = {v:g}")
    if rep.get("workers"):
        w = rep["workers"]
        wall = w["respawn_wall_s"]
        wall_txt = (f" wall max={wall['max']:g}s total={wall['total']:g}s"
                    if wall else "")
        failed = (f" ({w['respawn_failed']} failed)"
                  if w["respawn_failed"] else "")
        lines.append(
            f"worker lifecycle: {w['exits']} exit(s) "
            f"{w['exits_by_reason']}, {w['respawns']} "
            f"respawn(s){failed}{wall_txt}, "
            f"{w['heartbeat_misses']} heartbeat miss(es), "
            f"{w['migrations']} migration(s)")
    lines.append(f"events: {rep['events']}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="path to a Tracer.export JSON file")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary dict")
    ap.add_argument("--top", type=int, default=5,
                    help="top-N preempted/migrated requests to list")
    args = ap.parse_args()
    with open(args.trace) as f:
        doc = json.load(f)
    rep = analyze(doc, top=args.top)
    try:
        print(json.dumps(rep) if args.json else format_report(rep))
    except BrokenPipeError:      # head/less closed the pipe — fine
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
