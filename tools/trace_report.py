#!/usr/bin/env python
"""Summarize a serving telemetry trace (ISSUE 12).

Reads the Chrome-trace/Perfetto JSON written by
``paddle_tpu.utils.telemetry.Tracer.export`` and prints the post-mortem
a red gate run (or a bench artifact) needs without opening the UI:

- per-phase latency breakdown: count / total / mean / p50 / p99 of
  every span name (queued, prefill, splice_wait, decode, ...);
- per-replica occupancy: span-busy seconds per replica track over the
  trace wall clock (an approximation — overlapping spans of different
  requests double-count busy time, so >100% means real concurrency);
- dispatch mix per replica (ragged/decode/prefill/spec counts);
- top preempted / migrated requests, with req ids and tenant
  attributes off the request-begin records;
- terminal-state counts and the event tally (retries, injected
  faults, breaker strikes, kv churn).

Pure host tool: no jax, no paddle_tpu import — runs anywhere the JSON
does.

    python tools/trace_report.py trace.json
    python tools/trace_report.py trace.json --json   # machine-readable
    python tools/trace_report.py trace.json --top 10
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict


def _pct(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    i = (len(xs) - 1) * p
    lo, hi = int(i), min(int(i) + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (i - lo)


def _pid_name(pid):
    # keep in sync with telemetry.FLEET_PID (no import: pure host tool)
    return "fleet" if pid == 1000 else f"replica{pid}"


def analyze(doc: dict, top: int = 5) -> dict:
    evts = doc.get("traceEvents", [])
    spans = [e for e in evts if e.get("ph") == "X"]
    insts = [e for e in evts if e.get("ph") == "i"]
    begins = {e.get("id"): e for e in evts if e.get("ph") == "b"}
    ends = {e.get("id"): e for e in evts if e.get("ph") == "e"}

    # -- per-phase latency breakdown ------------------------------------
    by_phase: dict = defaultdict(list)
    for s in spans:
        by_phase[s["name"]].append(s.get("dur", 0.0) / 1e6)
    phases = {}
    for name, durs in sorted(by_phase.items()):
        phases[name] = {
            "count": len(durs),
            "total_s": round(sum(durs), 4),
            "mean_s": round(sum(durs) / len(durs), 5),
            "p50_s": round(_pct(durs, 0.50), 5),
            "p99_s": round(_pct(durs, 0.99), 5),
        }

    # -- per-replica occupancy + dispatch mix ---------------------------
    ts_all = [e["ts"] for e in evts if e.get("ph") in ("X", "i", "b", "e")]
    wall_s = ((max(ts_all) - min(ts_all)) / 1e6) if ts_all else 0.0
    busy: Counter = Counter()
    for s in spans:
        # waiting phases are not device work: a queue-backed-up idle
        # replica must not read as saturated
        if s["name"] in ("queued", "splice_wait"):
            continue
        busy[s["pid"]] += s.get("dur", 0.0) / 1e6
    dispatch_mix: dict = defaultdict(Counter)
    for e in insts:
        if e["name"] == "dispatch":
            dispatch_mix[e["pid"]][e.get("args", {}).get("kind", "?")] \
                += 1
    replicas = {}
    for pid in sorted(set(busy) | set(dispatch_mix)):
        replicas[_pid_name(pid)] = {
            "busy_s": round(busy.get(pid, 0.0), 4),
            "occupancy": (round(busy.get(pid, 0.0) / wall_s, 4)
                          if wall_s else None),
            "dispatches": dict(dispatch_mix.get(pid, {})),
        }

    # -- per-request robustness: preempt / migrate counts ---------------
    preempts: Counter = Counter()
    migrations: Counter = Counter()
    for e in insts:
        tid = e.get("tid")
        if e["name"] == "preempt" and tid:
            preempts[tid] += 1
        elif e["name"] == "migrate" and tid:
            migrations[tid] += 1

    def _req_label(tid):
        b = begins.get(str(tid)) or begins.get(tid)
        if b is None:
            return {"trace": tid}
        a = b.get("args", {})
        out = {"trace": tid, "req_id": a.get("req_id")}
        if "tenant" in a:
            out["tenant"] = a["tenant"]
        return out

    top_preempted = [dict(_req_label(t), preemptions=n)
                     for t, n in preempts.most_common(top)]
    top_migrated = [dict(_req_label(t), migrations=n)
                    for t, n in migrations.most_common(top)]

    # -- terminal states + event tally ----------------------------------
    states: Counter = Counter()
    for e in ends.values():
        states[e.get("args", {}).get("state", "?")] += 1
    events: Counter = Counter(e["name"] for e in insts)

    return {
        "wall_s": round(wall_s, 4),
        "records": len(evts),
        "dropped_records": doc.get("otherData", {}).get(
            "dropped_records", 0),
        "requests": {"begun": len(begins), "ended": len(ends),
                     "states": dict(states)},
        "phases": phases,
        "replicas": replicas,
        "top_preempted": top_preempted,
        "top_migrated": top_migrated,
        "events": dict(events),
    }


def format_report(rep: dict) -> str:
    lines = [f"trace: {rep['records']} records over {rep['wall_s']}s "
             f"wall ({rep['dropped_records']} dropped from the ring)"]
    rq = rep["requests"]
    lines.append(f"requests: {rq['begun']} begun, {rq['ended']} ended "
                 f"{rq['states']}")
    lines.append("per-phase latency:")
    for name, p in rep["phases"].items():
        lines.append(
            f"  {name:12s} n={p['count']:<5d} total={p['total_s']:<9g} "
            f"mean={p['mean_s']:<9g} p50={p['p50_s']:<9g} "
            f"p99={p['p99_s']:g}")
    lines.append("per-replica occupancy:")
    for name, r in rep["replicas"].items():
        occ = (f"{r['occupancy'] * 100:.1f}%"
               if r["occupancy"] is not None else "n/a")
        lines.append(f"  {name:10s} busy={r['busy_s']}s ({occ}) "
                     f"dispatches={r['dispatches']}")
    if rep["top_preempted"]:
        lines.append(f"top preempted: {rep['top_preempted']}")
    if rep["top_migrated"]:
        lines.append(f"top migrated: {rep['top_migrated']}")
    lines.append(f"events: {rep['events']}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="path to a Tracer.export JSON file")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary dict")
    ap.add_argument("--top", type=int, default=5,
                    help="top-N preempted/migrated requests to list")
    args = ap.parse_args()
    with open(args.trace) as f:
        doc = json.load(f)
    rep = analyze(doc, top=args.top)
    try:
        print(json.dumps(rep) if args.json else format_report(rep))
    except BrokenPipeError:      # head/less closed the pipe — fine
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
