#!/usr/bin/env python
"""Export serving metrics as OpenMetrics / Prometheus text (ISSUE 14).

Reads either a ``Tracer.export`` trace JSON (whose ``"metrics"`` key
carries the registry snapshot) or a bare ``MetricsRegistry.snapshot()``
JSON, and prints the OpenMetrics text exposition — counters with the
``_total`` suffix, gauges, cumulative-bucket histograms, ``# EOF``
terminated — so any Prometheus-compatible collector can scrape a gate
artifact or a bench export without a jax install.

Pure host tool: the formatter lives in
``paddle_tpu.utils.telemetry.openmetrics_text`` which imports numpy
only; when even that import fails (a bare laptop reading an artifact)
a vendored fallback formats the snapshot identically.

    python tools/metrics_export.py serving_trace.perfetto.json
    python tools/metrics_export.py snapshot.json -o metrics.prom
"""
from __future__ import annotations

import argparse
import json
import sys


def _load_snapshot(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    # a Tracer.export doc nests the snapshot under "metrics"; a bare
    # snapshot IS the dict (counters/gauges/histograms keys)
    if "metrics" in doc and isinstance(doc["metrics"], dict):
        return doc["metrics"]
    return doc


def _name(name):
    s = "".join(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                else "_" for ch in str(name))
    return ("_" + s) if (not s or s[0].isdigit()) else s


def _num(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".10g")


def _fallback_text(snapshot):
    """Vendored copy of telemetry.openmetrics_text for machines where
    even the numpy-only paddle_tpu import fails. Module-level (not
    hidden inside _formatter) ON PURPOSE: the parity test in
    tests/test_program_observatory.py formats one snapshot through
    BOTH implementations and asserts byte-equality, so an edit to the
    real exporter that forgets this copy fails loudly instead of
    silently shipping differently-shaped metrics to the exact
    environments the fallback exists for."""
    lines = []
    for name, v in sorted((snapshot.get("counters") or {}).items()):
        n = _name(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n}_total {_num(v)}")
    for name, v in sorted((snapshot.get("gauges") or {}).items()):
        n = _name(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_num(v)}")
    for name, h in sorted(
            (snapshot.get("histograms") or {}).items()):
        n = _name(name)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        counts = list(h.get("counts", ()))
        for b, c in zip(list(h.get("buckets", ())), counts):
            cum += int(c)
            lines.append(f'{n}_bucket{{le="{_num(b)}"}} {cum}')
        if counts:
            cum += int(counts[-1])
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{n}_sum {_num(h.get('sum', 0.0))}")
        lines.append(f"{n}_count {int(h.get('n', 0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _formatter():
    try:
        from paddle_tpu.utils.telemetry import openmetrics_text
        return openmetrics_text
    except Exception:       # noqa: BLE001 — no paddle_tpu/numpy here
        return _fallback_text


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="trace JSON (Tracer.export) or a bare "
                    "MetricsRegistry.snapshot() JSON")
    ap.add_argument("-o", "--out", default=None,
                    help="write here instead of stdout")
    args = ap.parse_args()
    text = _formatter()(_load_snapshot(args.path))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        try:
            sys.stdout.write(text)
        except BrokenPipeError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
