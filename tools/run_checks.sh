#!/usr/bin/env bash
# One-command CI gate: static analysis + runtime serving invariants +
# tier-1 pytest. Exits non-zero on ANY finding or test failure.
#
#   tools/run_checks.sh            # everything
#   tools/run_checks.sh --fast     # skip the tier-1 pytest sweep
#
# Phases:
#   1. flightcheck over paddle_tpu/ (AST rules FC1xx-FC7xx incl. the
#      SPMD/sharding and memory-hazard families, committed baseline, on-disk findings
#      cache; see tools/flightcheck/ and README "Static analysis").
#      Tip: `python -m tools.flightcheck --changed paddle_tpu/` scopes
#      a local run to git-modified files.
#   2. flightcheck --jaxpr: trace the serving/paged-decode entry points
#      and cross-check the AST verdicts + IR-level PRNG audit
#   3. comm audit: abstract-trace the distributed entry points on the
#      8-device mesh and pin each program's collectives (kind/axis/
#      bytes/count) against tools/flightcheck/comm_expectations.json
#   4. mem audit: abstract-trace the SAME entry points and pin each
#      program's memory shape (argument/output/peak-temp bytes,
#      donated bytes actually aliased, scan-carry residency) against
#      tools/flightcheck/mem_expectations.json, plus the cross-program
#      relations (int8 pool < fp32, multi-step carry flat in k, dp2
#      byte-identical to fp32)
#   5. serving invariant gate (PADDLE_TPU_POOL_DEBUG=1 over the
#      serving-path tests incl. test_fault_tolerance.py and
#      test_ragged_batching.py; includes its own paddle_tpu/ flightcheck
#      AND the deterministic chaos schedule across all nine legs —
#      dense/ragged/ragged_kv8/tp2/spec/lora/dp2/ragged_ms4/dp_proc —
#      every gate run exercises >=1 OOM-preemption, >=1 injected
#      dispatch failure and >=1 cancellation (the dp2 leg instead
#      demands >=1 replica failover and >=1 migrated-request
#      completion; the ragged_ms4 leg additionally demands >=1
#      multi-step fused window dispatched; the dp_proc leg SIGKILLs a
#      worker process mid-run and demands >=1 worker exit, >=1
#      respawn and >=1 migrated completion), with token-identity vs
#      a fault-free replay)
#   6. tier-1 pytest (tests/, -m 'not slow')
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
rc=0

echo "== [1/6] flightcheck: static analysis over paddle_tpu/ =="
python -m tools.flightcheck paddle_tpu/ || rc=1

echo "== [2/6] flightcheck --jaxpr: entry-point cross-check =="
python -m tools.flightcheck --jaxpr paddle_tpu/inference/ || rc=1

echo "== [3/6] comm audit: distributed collectives vs expectations =="
python -m tools.flightcheck.comm_audit || rc=1

echo "== [4/6] mem audit: per-program HBM bytes vs expectations =="
python -m tools.flightcheck.mem_audit || rc=1

echo "== [5/6] serving invariants (runtime debug_check + chaos gate) =="
# the invariants gate skips its own audit legs — phases 3 and 4 just ran
FLIGHTCHECK_COMM_AUDIT_RAN=1 FLIGHTCHECK_MEM_AUDIT_RAN=1 \
    python tools/check_serving_invariants.py || rc=1

if [ "${1:-}" != "--fast" ]; then
    echo "== [6/6] tier-1 pytest =="
    python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:randomly || rc=1
else
    echo "== [6/6] tier-1 pytest skipped (--fast) =="
fi

if [ "$rc" -ne 0 ]; then
    echo "run_checks: FAILED"
else
    echo "run_checks: all gates green"
fi
exit "$rc"
