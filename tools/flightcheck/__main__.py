"""flightcheck CLI — see package docstring for the rule catalog.

Exit codes: 0 = clean (or only baselined findings), 1 = new findings,
2 = usage error.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys


def changed_files(repo_root: str, run=subprocess.run):
    """Tracked-modified + untracked .py files (git-diff scoped mode).
    Returns None when git state is unreadable (caller falls back to a
    full run — degrading to MORE coverage, never less)."""
    try:
        diff = run(["git", "-C", repo_root, "diff", "--name-only",
                    "HEAD"], capture_output=True, text=True, timeout=30)
        untracked = run(["git", "-C", repo_root, "ls-files", "--others",
                         "--exclude-standard"], capture_output=True,
                        text=True, timeout=30)
        if diff.returncode or untracked.returncode:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    files = set(diff.stdout.splitlines()) | \
        set(untracked.stdout.splitlines())
    out = []
    for f in sorted(files):
        if f.endswith(".py"):
            ap = os.path.join(repo_root, f)
            if os.path.exists(ap):
                out.append(ap)
    return out


def _explain(code: str) -> int:
    from . import core
    docs = core.all_rules()
    code = code.upper()
    if code not in docs:
        print(f"unknown rule {code}; known: {', '.join(docs)}",
              file=sys.stderr)
        return 2
    print(f"{code}: {docs[code]}\n")
    rationale = core.RULE_EXPLAIN.get(code)
    if rationale:
        print(rationale + "\n")
    repo = core._REPO_ROOT
    fixtures = os.path.join(repo, "tests", "fixtures", "flightcheck")
    for kind, title in (("bad", "known-bad example (fires)"),
                        ("good", "corrected twin (clean)")):
        path = os.path.join(fixtures, f"{code.lower()}_{kind}.py")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                print(f"--- {title}: {os.path.relpath(path, repo)}")
                print(fh.read())
    return 0


def main(argv=None) -> int:
    from . import core, DEFAULT_BASELINE

    ap = argparse.ArgumentParser(
        prog="python -m tools.flightcheck",
        description="Framework-aware static analysis for JAX/TPU "
                    "hazard classes.")
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the committed one); "
                         "'' disables")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule codes to run (default "
                         "all); a bare family prefix like FC6 selects "
                         "the family")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="FC###",
                    help="print a rule's rationale plus its bad/good "
                         "fixture pair, then exit")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also trace the paged-decode/serving entry "
                    "points and cross-check AST verdicts")
    ap.add_argument("--comm-audit", action="store_true",
                    help="also run the distributed communication audit "
                         "against the committed expectations")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files modified/untracked per git "
                         "(scoped to the given paths when provided)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the on-disk findings cache")
    ap.add_argument("--show-baselined", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in core.all_rules().items():
            print(f"{code}  {doc}")
        return 0
    if args.explain:
        return _explain(args.explain)

    repo_root = core._REPO_ROOT
    if args.write_baseline and args.changed:
        # a baseline written from a git-scoped subset would silently
        # drop every entry living in unchanged files
        print("--write-baseline needs a full run; drop --changed",
              file=sys.stderr)
        return 2
    paths = args.paths
    changed_empty = False
    if args.changed:
        files = changed_files(repo_root)
        if files is None:
            # fall back to MORE coverage, never less: lint the given
            # paths in full — and with no paths there is no scope at
            # all, which must not read as clean
            print("flightcheck: git state unreadable; falling back to "
                  "a full run of the given paths", file=sys.stderr)
            if not paths:
                print("flightcheck: --changed without readable git "
                      "needs explicit paths", file=sys.stderr)
                return 2
        else:
            if paths:
                roots = [os.path.abspath(p) for p in paths]
                files = [f for f in files
                         if any(os.path.abspath(f) == r
                                or os.path.abspath(f).startswith(
                                    r.rstrip(os.sep) + os.sep)
                                for r in roots)]
            if not files:
                print("flightcheck: no changed .py files in scope")
                changed_empty = True
            # an empty list still falls through: explicitly requested
            # --jaxpr/--comm-audit gates must run regardless
            paths = files
    if not paths and not changed_empty:
        ap.print_usage()
        return 2

    # a family prefix (FC6) expands to every registered rule in it
    rules = []
    for tok in (r.strip() for r in args.rules.split(",") if r.strip()):
        expanded = [c for c in core.all_rules() if c.startswith(tok)]
        rules.extend(expanded or [tok])
    rules = rules or None
    cache_path = None if args.no_cache else "default"
    new, old = [], []
    for path in paths:
        n, o = core.run(path, args.baseline or None, rules,
                        cache_path=cache_path)
        new.extend(n)
        old.extend(o)

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline needs a baseline path "
                  "(--baseline '' disables baselining)", file=sys.stderr)
            return 2
        core.write_baseline(args.baseline, new + old)
        print(f"baseline written: {len(new + old)} finding(s) -> "
              f"{args.baseline}")
        return 0

    jaxpr_failed = False
    if args.jaxpr:
        # cross-check BEFORE printing: refuted findings must not appear
        # as normal findings in a run that then reports clean
        from . import jaxpr_check
        report = jaxpr_check.cross_check(new)
        print(report.summary())
        new = report.confirmed
        # a trace failure OR an IR-level PRNG reuse is a confirmed
        # hazard regardless of what the AST pass saw
        jaxpr_failed = bool(report.trace_failures or report.prng_notes)

    comm_failed = False
    if args.comm_audit:
        # subprocess on purpose: this process's jax backend may already
        # be initialized with one device (the --jaxpr phase does), and
        # the audit needs the 8-device mesh from a clean start
        import subprocess
        comm_failed = subprocess.call(
            [sys.executable, "-m", "tools.flightcheck.comm_audit"],
            cwd=repo_root) != 0

    for f in new:
        print(core.format_finding(f))
    if args.show_baselined:
        for f in old:
            print("[baselined] " + core.format_finding(f))

    if new:
        print(f"\nflightcheck: {len(new)} new finding(s) "
              f"({len(old)} baselined)")
        return 1
    if jaxpr_failed or comm_failed:
        return 1
    print(f"flightcheck: clean ({len(old)} baselined finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
