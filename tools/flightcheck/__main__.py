"""flightcheck CLI — see package docstring for the rule catalog.

Exit codes: 0 = clean (or only baselined findings), 1 = new findings,
2 = usage error.
"""
from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from . import core, DEFAULT_BASELINE

    ap = argparse.ArgumentParser(
        prog="python -m tools.flightcheck",
        description="Framework-aware static analysis for JAX/TPU "
                    "hazard classes.")
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the committed one); "
                         "'' disables")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule codes to run (default "
                         "all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also trace the paged-decode/serving entry "
                         "points and cross-check AST verdicts")
    ap.add_argument("--show-baselined", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in core.all_rules().items():
            print(f"{code}  {doc}")
        return 0
    if not args.paths:
        ap.print_usage()
        return 2

    rules = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    new, old = [], []
    for path in args.paths:
        n, o = core.run(path, args.baseline or None, rules)
        new.extend(n)
        old.extend(o)

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline needs a baseline path "
                  "(--baseline '' disables baselining)", file=sys.stderr)
            return 2
        core.write_baseline(args.baseline, new + old)
        print(f"baseline written: {len(new + old)} finding(s) -> "
              f"{args.baseline}")
        return 0

    jaxpr_failed = False
    if args.jaxpr:
        # cross-check BEFORE printing: refuted findings must not appear
        # as normal findings in a run that then reports clean
        from . import jaxpr_check
        report = jaxpr_check.cross_check(new)
        print(report.summary())
        new = report.confirmed
        # a trace failure OR an IR-level PRNG reuse is a confirmed
        # hazard regardless of what the AST pass saw
        jaxpr_failed = bool(report.trace_failures or report.prng_notes)

    for f in new:
        print(core.format_finding(f))
    if args.show_baselined:
        for f in old:
            print("[baselined] " + core.format_finding(f))
    if jaxpr_failed:
        return 1

    if new:
        print(f"\nflightcheck: {len(new)} new finding(s) "
              f"({len(old)} baselined)")
        return 1
    print(f"flightcheck: clean ({len(old)} baselined finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
