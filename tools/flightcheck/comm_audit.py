"""Per-program communication audit: abstract-trace every distributed
entry point under an 8-device mesh and report each collective's kind,
axis, per-shard payload bytes, and count per dispatch.

This is the regression net ROADMAP item 1 (multi-chip TP serving) ships
under: the per-layer allreduce is about to become the serving hot path,
and an accidental implicit all-gather — or a doubled allreduce from a
refactor — is invisible to every numeric test (the math stays right,
the step just gets slower). The audit walks the traced jaxpr, so it
counts exactly what the program will execute:

- ``scan`` bodies multiply by the trip count (a per-tick ppermute in an
  n-tick pipeline counts n times);
- ``cond``/``switch`` branches merge by elementwise max (the worst-case
  schedule);
- ``while`` bodies count ONCE and the program is marked approximate.

Entry points: the eager collective bodies (collective.py — the SAME
module-level body functions the public API jits; the EQuARX-style
int8_all_reduce included), ring attention forward/backward (zigzag and
the multi-axis fallback), the GPipe pipeline, the table-driven 1F1B
schedule, the full 4D-parallel pipelined-Llama train step, and (ISSUE
8) the TENSOR-PARALLEL SERVING STEP — the ServingEngine(tp=2) ragged
[T, W] program, fp32 and int8 comms, whose expectations pin exactly
one allreduce per attention/MLP block per layer per ministep, one
logits all_gather per ministep, and ZERO collectives on the KV-append
path (any implicit gather there would change the counts).

The committed expectations file (tools/flightcheck/comm_expectations.json)
pins every program's audit; ``python -m tools.flightcheck.comm_audit``
fails on ANY drift. Regenerate deliberately with ``--write`` after a
reviewed change.
"""
from __future__ import annotations

import json
import os
import sys
from collections import Counter
from typing import Dict, List, Optional, Tuple

EXPECTATIONS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "comm_expectations.json")

# data-moving collective primitives (axis_index/pvary move nothing)
COMM_PRIMS = {"psum", "pmax", "pmin", "ppermute", "pshuffle",
              "all_gather", "all_to_all", "psum_scatter",
              "reduce_scatter", "pbroadcast"}

_N_DEV = 8


def ensure_devices(n: int = _N_DEV):
    """Force an n-device CPU backend (the conftest dance, usable
    standalone): must run before anything initializes a jax backend."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    from jax._src import xla_bridge as _xb
    if not _xb.backends_are_initialized():
        _xb._backend_factories.pop("axon", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}")
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"comm audit needs {n} devices, found {len(jax.devices())} "
            f"(backend initialized too early?)")


# -- jaxpr walking ----------------------------------------------------------

def _axis_of(params) -> str:
    ax = params.get("axes", params.get("axis_name"))
    if isinstance(ax, (tuple, list)):
        return ",".join(str(a) for a in ax)
    return str(ax)


def _nbytes(eqn) -> int:
    import numpy as np
    total = 0
    for v in eqn.invars:
        if hasattr(v, "val"):        # literal
            continue
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            total += int(np.prod(aval.shape, dtype=np.int64)
                         * np.dtype(aval.dtype).itemsize)
    return total


def _walk(jx, mult: int, acc: Counter, flags: set):
    for eqn in jx.eqns:
        prim = eqn.primitive.name
        if prim in COMM_PRIMS:
            axis = _axis_of(eqn.params)
            if axis:    # psum(axes=()) appears in transposed shard_map
                acc[(prim, axis, _nbytes(eqn))] += mult  # bodies; no-op
            continue
        if prim == "scan":
            _walk(eqn.params["jaxpr"].jaxpr,
                  mult * int(eqn.params["length"]), acc, flags)
            continue
        if prim == "while":
            flags.add("while-approx")   # trip count unknown: count once
            _walk(eqn.params["body_jaxpr"].jaxpr, mult, acc, flags)
            _walk(eqn.params["cond_jaxpr"].jaxpr, mult, acc, flags)
            continue
        if prim in ("cond", "switch"):
            best: Counter = Counter()
            for br in eqn.params["branches"]:
                c: Counter = Counter()
                _walk(br.jaxpr, mult, c, flags)
                for k, v in c.items():
                    best[k] = max(best[k], v)
            for k, v in best.items():
                acc[k] += v
            continue
        for v in eqn.params.values():
            _recurse(v, mult, acc, flags)


def _recurse(v, mult, acc, flags):
    core = getattr(v, "jaxpr", None)
    if core is not None and hasattr(core, "eqns"):
        _walk(core, mult, acc, flags)
    elif hasattr(v, "eqns"):
        _walk(v, mult, acc, flags)
    elif isinstance(v, (tuple, list)):
        for s in v:
            _recurse(s, mult, acc, flags)


def audit_jaxpr(closed_jaxpr) -> Tuple[List[dict], List[str]]:
    """-> (rows sorted by (kind, axis, bytes), approximation flags).
    Row: {kind, axis, bytes (per-shard payload), count (per dispatch)}."""
    acc: Counter = Counter()
    flags: set = set()
    _walk(closed_jaxpr.jaxpr, 1, acc, flags)
    rows = [{"kind": k, "axis": a, "bytes": b, "count": int(n)}
            for (k, a, b), n in acc.items()]
    rows.sort(key=lambda r: (r["kind"], r["axis"], r["bytes"]))
    return rows, sorted(flags)


# -- entry-point registry ---------------------------------------------------

def _mesh1d(name="rank", n=_N_DEV):
    import jax
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:n]), (name,))


def _collective_program(body, out_spec, shape, in_spec=None):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = _mesh1d()
    f = shard_map(body, mesh=mesh, in_specs=(in_spec or P("rank"),),
                  out_specs=out_spec, check_vma=False)
    return f, (jax.ShapeDtypeStruct(shape, jnp.float32),)


def _build_collectives():
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import collective as C
    n = _N_DEV
    ring = [(i, (i + 1) % n) for i in range(n)]
    return {
        "collective.all_reduce": lambda: _collective_program(
            C.all_reduce_body(C.ReduceOp.SUM), P("rank"), (n, 64, 64)),
        "collective.all_gather": lambda: _collective_program(
            C.all_gather_body(), P(), (n, 64, 64)),
        "collective.broadcast": lambda: _collective_program(
            C.broadcast_body(0), P("rank"), (n, 64, 64)),
        "collective.reduce": lambda: _collective_program(
            C.reduce_body(C.ReduceOp.SUM, 0), P("rank"), (n, 64, 64)),
        "collective.reduce_scatter": lambda: _collective_program(
            C.reduce_scatter_body(), P("rank"), (n, n)),
        "collective.all_to_all": lambda: _collective_program(
            C.all_to_all_body(), P("rank"), (n, n, 16)),
        "collective.barrier": lambda: _collective_program(
            C.barrier_body(), P("rank"), (n,)),
        "collective.p2p_ring": lambda: _collective_program(
            C.ppermute_body(ring), P("rank"), (n, 64, 64)),
        # the EQuARX-style quantized allreduce (ISSUE 8): its exact
        # collective shape — TWO all_to_alls (int8 chunks + their
        # per-row scales, the reduce-scatter phase) + TWO all_gathers
        # (reduced int8 chunks + fresh scales) — is pinned here so a
        # refactor that silently doubles a phase (or falls back to
        # fp32 psum) fails the gate
        "collective.int8_all_reduce": lambda: _collective_program(
            C.int8_all_reduce_body(n), P("rank"), (n, 4, 64)),
    }


def _build_ring_attention():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from paddle_tpu.distributed.ring_attention import ring_attention

    def fwd():
        mesh = _mesh1d("sep")
        q = jax.ShapeDtypeStruct((1, 128, 4, 16), jnp.float32)
        return (lambda a, b, c: ring_attention(
            a, b, c, mesh, axis="sep", use_pallas=False)), (q, q, q)

    def grad():
        mesh = _mesh1d("sep")
        q = jax.ShapeDtypeStruct((1, 128, 4, 16), jnp.float32)

        def loss(a, b, c):
            return ring_attention(a, b, c, mesh, axis="sep",
                                  use_pallas=False).sum()
        return jax.grad(loss, argnums=(0, 1, 2)), (q, q, q)

    def multiaxis():
        import jax as _j
        mesh = Mesh(np.asarray(_j.devices()[:8]).reshape(2, 4),
                    ("dp", "sep"))
        q = jax.ShapeDtypeStruct((2, 64, 4, 16), jnp.float32)
        return (lambda a, b, c: ring_attention(
            a, b, c, mesh, axis="sep", use_pallas=False)), (q, q, q)

    return {"ring_attention.zigzag_fwd": fwd,
            "ring_attention.zigzag_grad": grad,
            "ring_attention.multiaxis_fwd": multiaxis}


def _build_pipelines():
    import jax
    import jax.numpy as jnp

    def gpipe():
        from paddle_tpu.distributed.fleet.pipeline import pipeline_apply
        mesh = _mesh1d("pp")
        d, m, b = 16, 8, 4
        w = jax.ShapeDtypeStruct((8, d, d), jnp.float32)
        xs = jax.ShapeDtypeStruct((m, b, d), jnp.float32)
        return (lambda wp, x: pipeline_apply(
            lambda p, a: jnp.tanh(a @ p), wp, x, mesh)), (w, xs)

    def onef1b():
        from paddle_tpu.distributed.fleet.pp_schedule import (
            build_pipeline_schedule, make_pipeline_loss_fn)
        mesh = _mesh1d("pp")
        d, m, b, p = 16, 8, 4, 8
        sched = build_pipeline_schedule(p, m, 1, "1F1B")

        def stage_fn(pj, x):
            return jnp.tanh(x @ pj["w"])

        def loss_fn(lp, out, y):
            return jnp.mean((out * lp - y) ** 2)

        ploss = make_pipeline_loss_fn(stage_fn, loss_fn, mesh, sched)
        sp = {"w": jax.ShapeDtypeStruct((1, p, d, d), jnp.float32)}
        lp = jax.ShapeDtypeStruct((d,), jnp.float32)
        xs = jax.ShapeDtypeStruct((m, b, d), jnp.float32)
        ys = jax.ShapeDtypeStruct((m, b, d), jnp.float32)
        return ploss, (sp, lp, xs, ys)

    return {"pipeline.gpipe": gpipe, "pp_schedule.1f1b": onef1b}


def _build_llama_pp():
    def step():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh
        from paddle_tpu.models.llama_pp import (PipelinedLlamaConfig,
                                                build_pipelined_llama_step)
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ("pp", "mp", "dp"))
        cfg = PipelinedLlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_attention_heads=4, num_key_value_heads=2,
            layers_per_chunk=1, vpp_degree=1, max_seq_len=32)
        n_micro, micro_b, seq = 4, 2, 16
        state, step_fn, _ = build_pipelined_llama_step(
            cfg, mesh, n_micro, micro_b, seq)
        ids = jnp.zeros((n_micro * micro_b, seq), jnp.int32)
        return step_fn, (state, ids, ids)

    return {"llama_pp.train_step": step}


def _build_tp_serving():
    """The ISSUE-8 serving-step programs: the unified ragged [T, W]
    chunk of a ServingEngine(tp=2) on a 2-device submesh, fp32 and
    int8 comms. The pinned expectations ARE the TP contract:

    - fp32: exactly ONE psum per attention/MLP block per layer per
      ministep (T * layers * 2 in total) plus ONE logits all_gather
      per ministep — and NOTHING else: the KV-append path
      (reshape_and_cache into the kv-head-sharded pool) contributes
      zero collectives, and a doubled/implicit collective from a
      refactor changes the counts and fails this gate in ~4s, not in
      a profile;
    - int8: each block psum becomes the quantized collective
      (2 all_to_alls + 2 all_gathers, chunks + per-row scales), the
      logits gather stays exact;
    - spec (ISSUE 9): the speculative VERIFY program
      (serving.ragged_spec_tp2) must have exactly the T=1 ragged
      program's collectives — one psum per block per layer plus one
      logits all_gather. In-program acceptance compares post-gather
      (replicated) tokens and the rejected-tail neutralization
      zero-scatters each shard's own kv-head slice, so verification
      adds ZERO collectives; any new collective here fails the gate.
    """
    def _mk(tp_comm, kv_quant=None):
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from paddle_tpu.inference.paged_decode import \
                PagedLlamaDecoder
            from paddle_tpu.inference.serving import ServingEngine
            from paddle_tpu.models.llama import LlamaConfig
            cfg = LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)
            mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
            dec = PagedLlamaDecoder.from_config(
                cfg, num_blocks=8, block_size=4, mesh=mesh,
                mp_axis="tp", tp_shard_map=True, tp_comm=tp_comm,
                kv_quant=kv_quant)
            eng = ServingEngine(dec, tp=2, tp_comm=tp_comm,
                                max_batch_size=2,
                                prompt_buckets=(8, 16), chunk_size=2,
                                prefill_chunk=4)
            T, W = 2, 4
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (dec.weights, dec.cache.k, dec.cache.v,
                    S((T, W), i32), S((W,), i32), S((W,), i32),
                    S((W,), jnp.bool_), S((W,), i32),
                    S((T, W), i32), S((T, W), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), i32),
                    S((T, W), jnp.bool_),
                    S((eng.max_b + 1, dec.max_pages), i32),
                    S((T, W), f32), S((T, 2), jnp.uint32))
            return eng._ragged_j, args
        return build

    def _mk_spec():
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from paddle_tpu.inference.paged_decode import \
                PagedLlamaDecoder
            from paddle_tpu.inference.serving import ServingEngine
            from paddle_tpu.inference.spec_decode import SpecConfig
            from paddle_tpu.models.llama import LlamaConfig
            cfg = LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)
            mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
            dec = PagedLlamaDecoder.from_config(
                cfg, num_blocks=8, block_size=4, mesh=mesh,
                mp_axis="tp", tp_shard_map=True, tp_comm="fp32")
            eng = ServingEngine(dec, tp=2, max_batch_size=2,
                                prompt_buckets=(8, 16), chunk_size=2,
                                prefill_chunk=4,
                                spec_decode=SpecConfig(draft_len=3))
            W = 8
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (dec.weights, dec.cache.k, dec.cache.v,
                    S((W,), i32), S((W,), jnp.bool_), S((W,), i32),
                    S((W,), i32), S((W,), i32), S((W,), i32),
                    S((W,), i32),
                    S((eng.max_b + 1, dec.max_pages), i32),
                    S((W,), f32), S((2,), jnp.uint32),
                    S((W,), i32), S((W,), jnp.bool_))
            return eng._spec_j, args
        return build

    def _mk_lora():
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from paddle_tpu.inference.lora import AdapterRegistry
            from paddle_tpu.inference.paged_decode import \
                PagedLlamaDecoder
            from paddle_tpu.inference.serving import ServingEngine
            from paddle_tpu.models.llama import LlamaConfig
            cfg = LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)
            mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
            dec = PagedLlamaDecoder.from_config(
                cfg, num_blocks=8, block_size=4, mesh=mesh,
                mp_axis="tp", tp_shard_map=True, tp_comm="fp32")
            reg = AdapterRegistry(rank=2)
            reg.register_random("tenant0", seed=0)
            eng = ServingEngine(dec, tp=2, max_batch_size=2,
                                prompt_buckets=(8, 16), chunk_size=2,
                                prefill_chunk=4, lora=reg)
            T, W = 2, 4
            lay = reg.layout
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (dec.weights, dec.cache.k, dec.cache.v,
                    S((dec.cache.num_blocks, lay.page_elems), f32),
                    S((2,), i32),
                    S((eng.max_b + 1, lay.n_pages), i32),
                    S((T, W), i32), S((W,), i32), S((W,), i32),
                    S((W,), jnp.bool_), S((W,), i32),
                    S((T, W), i32), S((T, W), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), i32),
                    S((T, W), jnp.bool_),
                    S((eng.max_b + 1, dec.max_pages), i32),
                    S((T, W), f32), S((T, 2), jnp.uint32))
            return eng._ragged_lora_j, args
        return build

    def _mk_ms():
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from paddle_tpu.inference.paged_decode import \
                PagedLlamaDecoder
            from paddle_tpu.inference.serving import ServingEngine
            from paddle_tpu.models.llama import LlamaConfig
            cfg = LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)
            mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
            dec = PagedLlamaDecoder.from_config(
                cfg, num_blocks=8, block_size=4, mesh=mesh,
                mp_axis="tp", tp_shard_map=True, tp_comm="fp32")
            eng = ServingEngine(dec, tp=2, tp_comm="fp32",
                                multi_step=4, max_batch_size=2,
                                prompt_buckets=(8, 16), chunk_size=2,
                                prefill_chunk=4)
            # the fused window: k * chunk_size ministeps in ONE
            # program (the shapes the scheduler dispatches when every
            # running slot is decoding), plus the per-column eos ids
            # the on-device finish bookkeeping consumes
            T, W = 4 * 2, 4
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (dec.weights, dec.cache.k, dec.cache.v,
                    S((T, W), i32), S((W,), i32), S((W,), i32),
                    S((W,), jnp.bool_), S((W,), i32),
                    S((T, W), i32), S((T, W), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), i32),
                    S((T, W), jnp.bool_),
                    S((eng.max_b + 1, dec.max_pages), i32),
                    S((T, W), f32), S((T, 2), jnp.uint32),
                    S((W,), i32))
            return eng._ragged_ms_j, args
        return build

    def _mk_dp():
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from paddle_tpu.distributed.spec_layout import SpecLayout
            from paddle_tpu.inference.fleet import Router
            from paddle_tpu.inference.paged_decode import \
                PagedLlamaDecoder
            from paddle_tpu.inference.serving import ServingEngine
            from paddle_tpu.models.llama import LlamaConfig
            cfg = LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)

            def factory(idx, devs):
                mesh = Mesh(np.asarray(devs), ("tp",))
                dec = PagedLlamaDecoder.from_config(
                    cfg, num_blocks=8, block_size=4, mesh=mesh,
                    mp_axis="tp", tp_shard_map=True, tp_comm="fp32")
                return ServingEngine(dec, tp=2, max_batch_size=2,
                                     prompt_buckets=(8, 16),
                                     chunk_size=2, prefill_chunk=4)

            router = Router(None, dp=2, tp=2, engine_factory=factory)
            # replica 1 — the row OFF the default device slice: its
            # placement comes from SpecLayout.fleet_device_slices and
            # proves a non-zero dp row's step program is byte-for-byte
            # the single-engine tp program
            eng = router.replicas[1].engine
            grid = SpecLayout().fleet_device_slices(2, 2)
            assert list(eng.dec.mesh.devices.ravel()) == grid[1]
            T, W = 2, 4
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (eng.dec.weights, eng.dec.cache.k, eng.dec.cache.v,
                    S((T, W), i32), S((W,), i32), S((W,), i32),
                    S((W,), jnp.bool_), S((W,), i32),
                    S((T, W), i32), S((T, W), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), i32),
                    S((T, W), jnp.bool_),
                    S((eng.max_b + 1, eng.dec.max_pages), i32),
                    S((T, W), f32), S((T, 2), jnp.uint32))
            return eng._ragged_j, args
        return build

    return {"serving.ragged_tp2_fp32": _mk("fp32"),
            "serving.ragged_tp2_int8": _mk("int8"),
            # ISSUE 13: the QUANTIZED-POOL ragged step must pin
            # byte-identical collectives to the fp32-pool program —
            # the int8 planes' sidecar scales shard dim-aligned with
            # their kv heads (canonical cache_k_scale spec), so the
            # quantize-at-append scatter and dequant-at-read gather
            # are both shard-local; ANY implicit gather over the
            # scales (a mis-sharded sidecar) changes these counts and
            # fails the 4s gate
            "serving.ragged_kv8_tp2": _mk("fp32", kv_quant="int8"),
            # ISSUE 16: the multi-step fused window at k=4 must pin
            # EXACTLY k x the per-ministep collectives of the T=2
            # baseline above (4x the T, 4x the psums and logits
            # gathers, nothing else): the scan carry (sampled tokens,
            # live mask, KV pool planes) is shard-local, the
            # on-device EOS bookkeeping compares post-gather
            # replicated tokens, and the per-iteration KV append
            # stays collective-free — a refactor that syncs the
            # carry or double-gathers logits changes these counts
            # and fails the 4s gate
            "serving.ragged_k4_tp2": _mk_ms(),
            "serving.ragged_spec_tp2": _mk_spec(),
            # ISSUE 11: a dp x tp FLEET replica's ragged step — built
            # through the Router on row 1 of the SpecLayout 2x2 device
            # grid — must pin EXACTLY the collectives of the
            # single-engine tp=2 program (serving.ragged_tp2_fp32):
            # data parallelism contributes ZERO step-path collectives
            # because replicas never talk during a step (affinity is a
            # host-side hash lookup, failover a host-side re-enqueue)
            "serving.ragged_dp2_tp2": _mk_dp(),
            # ISSUE 10: the multi-tenant lora twin of the fp32 ragged
            # step MUST pin exactly the base program's collectives —
            # the per-row adapter deltas (replicated pool gather,
            # per-shard A-row/B-column slices, row-parallel deltas
            # joining the partial product before the block psum) add
            # ZERO collectives; any new psum/all_gather here fails
            # the gate
            "serving.ragged_lora_tp2": _mk_lora()}


def programs() -> Dict[str, callable]:
    """name -> lazy builder returning (traceable fn, example args).
    Builders import jax/paddle_tpu only when called."""
    out: Dict[str, callable] = {}
    out.update(_build_collectives())
    out.update(_build_ring_attention())
    out.update(_build_pipelines())
    out.update(_build_llama_pp())
    out.update(_build_tp_serving())
    return out


def program_names() -> List[str]:
    return sorted(programs())


# -- audit / expectations ---------------------------------------------------

def audit(only: Optional[str] = None) -> Dict[str, dict]:
    """Trace and audit every registered program (or those whose name
    starts with ``only``). -> {name: {"collectives": rows, "flags":
    [...]}}; a trace failure becomes {"error": ...}."""
    ensure_devices()
    import jax
    report: Dict[str, dict] = {}
    for name, build in sorted(programs().items()):
        if only and not name.startswith(only):
            continue
        try:
            fn, args = build()
            jx = jax.make_jaxpr(fn)(*args)
            rows, flags = audit_jaxpr(jx)
            report[name] = {"collectives": rows, "flags": flags}
        except Exception as e:   # a program that cannot trace IS a bug
            report[name] = {"error": f"{type(e).__name__}: {e}"}
    return report


def save(report: Dict[str, dict], path: str = EXPECTATIONS):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load(path: str = EXPECTATIONS) -> Dict[str, dict]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def compare(actual: Dict[str, dict],
            expected: Dict[str, dict]) -> List[str]:
    """Human-readable drift list (empty = match). Only programs present
    in ``actual`` are compared (supports scoped runs), but a program
    expected and not even REGISTERED is drift."""
    problems: List[str] = []
    names = set(programs())
    for name in sorted(set(expected) - names):
        problems.append(f"{name}: expected but no longer registered")
    for name, got in sorted(actual.items()):
        want = expected.get(name)
        if want is None:
            problems.append(f"{name}: not in expectations file "
                            f"(regenerate with --write)")
            continue
        if "error" in got:
            problems.append(f"{name}: TRACE FAILURE {got['error']}")
            continue
        if got != want:
            problems.append(
                f"{name}: communication drift\n"
                f"    expected: {json.dumps(want.get('collectives'))}\n"
                f"    actual:   {json.dumps(got.get('collectives'))}")
    return problems


def format_report(report: Dict[str, dict]) -> str:
    lines = []
    for name, entry in sorted(report.items()):
        if "error" in entry:
            lines.append(f"{name}: TRACE FAILURE {entry['error']}")
            continue
        rows = entry["collectives"]
        flag = (" [" + ",".join(entry["flags"]) + "]"
                if entry.get("flags") else "")
        if not rows:
            lines.append(f"{name}: no collectives{flag}")
            continue
        lines.append(f"{name}:{flag}")
        for r in rows:
            lines.append(f"    {r['kind']:<14} axis={r['axis']:<8} "
                         f"{r['bytes']:>10} B  x{r['count']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m tools.flightcheck.comm_audit",
        description="jaxpr-level communication audit of the "
                    "distributed entry points")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the committed expectations file")
    ap.add_argument("--only", default=None,
                    help="audit only programs with this name prefix")
    args = ap.parse_args(argv)

    report = audit(only=args.only)
    if args.only and not report:
        print(f"comm audit: --only {args.only!r} matches no registered "
              f"program; known: {', '.join(program_names())}",
              file=sys.stderr)
        return 2
    print(format_report(report))
    errors = [n for n, e in report.items() if "error" in e]
    if args.write:
        if errors:
            print(f"comm audit: NOT writing expectations — "
                  f"{len(errors)} trace failure(s)")
            return 1
        if args.only:
            merged = load() if os.path.exists(EXPECTATIONS) else {}
            merged.update(report)
            report = merged
        save(report)
        print(f"comm audit: expectations written -> {EXPECTATIONS}")
        return 0
    if not os.path.exists(EXPECTATIONS):
        print("comm audit: no expectations file committed — run with "
              "--write")
        return 1
    problems = compare(report, load())
    if problems:
        print("\ncomm audit: DRIFT detected")
        for p in problems:
            print("  " + p)
        return 1
    print(f"\ncomm audit: {len(report)} program(s) match the committed "
          f"expectations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
