"""Tracer-safety rules (FC101/FC102/FC103): Python control flow and host
conversion on traced values inside a JAX trace.

Hazard: inside ``@jax.jit`` / ``shard_map`` / ``lax.scan``-style scopes,
array arguments are abstract tracers. ``if x > 0``, ``bool(x)``,
``x.item()`` or ``np.asarray(x)`` either raises a
``ConcretizationTypeError`` at trace time or — worse, when the value
happens to be a concrete constant on the first trace — silently bakes
one branch into the compiled program (the classic "works in the test,
wrong in production" tracer leak). This repo's serving engine compiles
every hot path (``ServingEngine.__init__`` wraps prefill/decode in
``jax.jit``); a stray Python branch in one of those closures would
freeze the first request's schedule into all later dispatches.

Real example from this tree: ``paddle_tpu/inference/serving.py``'s
``decode_chunk`` runs under ``jax.jit`` + ``lax.scan`` — every decision
inside it (sampling, masking) is correctly expressed as ``jnp.where``;
FC101 is the rule that keeps it that way.

Rules:
- FC101: ``if``/``while``/ternary/``assert`` condition value-uses a
  traced argument (or a value derived from one).
- FC102: explicit ``bool()``/``int()``/``float()`` cast of a traced
  value.
- FC103: host materialization of a traced value — ``.item()`` /
  ``.tolist()`` / ``.numpy()`` or a ``np.*`` call on it.
"""
from __future__ import annotations

import ast
from typing import List

from .core import Finding, FileContext
from .scopes import (FuncNode, dotted, find_traced_scopes, func_of_map,
                     propagate_taint, tail_of, value_uses)

_CAST_HEADS = {"bool", "int", "float", "complex"}
_HOST_METHODS = {"item", "tolist", "numpy", "block_until_ready",
                 "copy_to_host_async"}
_NP_PREFIXES = ("np.", "numpy.")
# np calls that are shape/metadata-only and safe on tracers' metadata
_NP_SAFE_TAILS = {"dtype", "shape", "ndim"}


def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    owner_of = func_of_map(tree)
    for scope in find_traced_scopes(tree):
        node = scope.node
        params = set(scope.traced_params())
        if not params:
            continue
        tainted = propagate_taint(node, params)

        body = node.body if not isinstance(node, ast.Lambda) \
            else [ast.Expr(node.body)]
        for sub in _walk_same_scope(node):
            qn = owner_of.get(sub, scope.qualname) or scope.qualname
            # FC101: control flow on traced value
            test = None
            if isinstance(sub, (ast.If, ast.While)):
                test = sub.test
            elif isinstance(sub, ast.IfExp):
                test = sub.test
            elif isinstance(sub, ast.Assert):
                test = sub.test
            if test is not None:
                hits = value_uses(test, tainted)
                if hits:
                    kind = type(sub).__name__.lower()
                    findings.append(Finding(
                        ctx.path, sub.lineno, "FC101",
                        f"Python `{kind}` on traced value "
                        f"'{hits[0].id}' inside jit scope "
                        f"({scope.reason}); use jnp.where/lax.cond or "
                        f"mark the argument static", qn))
            if isinstance(sub, ast.Call):
                head = dotted(sub.func)
                tail = tail_of(head)
                # FC102: bool(x)/int(x)/float(x)
                if head in _CAST_HEADS and sub.args:
                    hits = value_uses(sub.args[0], tainted)
                    if hits:
                        findings.append(Finding(
                            ctx.path, sub.lineno, "FC102",
                            f"`{head}()` cast of traced value "
                            f"'{hits[0].id}' inside jit scope forces a "
                            f"trace-time concretization", qn))
                # FC103: .item()/.tolist()/np.* on traced value
                elif (isinstance(sub.func, ast.Attribute)
                      and sub.func.attr in _HOST_METHODS):
                    hits = value_uses(sub.func.value, tainted)
                    if hits:
                        findings.append(Finding(
                            ctx.path, sub.lineno, "FC103",
                            f"`.{sub.func.attr}()` on traced value "
                            f"'{hits[0].id}' inside jit scope is a "
                            f"host sync / trace error", qn))
                elif head and head.startswith(_NP_PREFIXES) \
                        and tail not in _NP_SAFE_TAILS:
                    hits = []
                    for a in sub.args:
                        hits = value_uses(a, tainted)
                        if hits:
                            break
                    if hits:
                        findings.append(Finding(
                            ctx.path, sub.lineno, "FC103",
                            f"`{head}()` applied to traced value "
                            f"'{hits[0].id}' inside jit scope "
                            f"materializes on host; use the jnp "
                            f"equivalent", qn))
    # dedupe (nested traced scopes can visit the same node twice)
    seen = set()
    out = []
    for f in findings:
        key = (f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _walk_same_scope(fn_node):
    """Walk a function body but do NOT descend into nested defs — they
    are separate traced scopes with their own parameters."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FuncNode + (ast.Lambda,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def setup(register):
    register("tracer_safety", check, {
        "FC101": "Python if/while/assert on a traced value in jit scope",
        "FC102": "bool/int/float cast of a traced value in jit scope",
        "FC103": "host materialization (.item/np.*) of a traced value",
    })
