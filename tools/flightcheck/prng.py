"""PRNG-discipline rules (FC401/FC402): key reuse and dead derivations.

Hazard: JAX PRNG keys are values, not stateful generators. Passing the
SAME key into two sampling primitives yields perfectly correlated
"randomness" — e.g. feeding one key to two ``jax.random.categorical``
calls samples identical tokens, which in a serving engine silently
degrades every temperature>0 request (no test that checks
"output is random-ish" catches two streams being EQUAL). The fix is
``key, sub = jax.random.split(key)`` before each consumption — exactly
the ``ServingEngine._next_key`` discipline in this repo
(``serving.py``), where every dispatch derives a fresh subkey and the
decode chunk pre-splits ``jax.random.split(key, T)`` for its scan.

Rules:
- FC401: a key variable consumed by two calls (or re-consumed across a
  loop iteration) without an intervening ``split``/``fold_in``
  rebinding. ``split(key)`` counts as a consumption of ``key`` too —
  using ``key`` again AFTER splitting it is the classic reuse.
- FC402: a ``split``/``fold_in`` result that is never used — deriving
  entropy and dropping it usually means the OLD key kept being used.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FileContext
from .scopes import FuncNode, dotted, func_of_map, tail_of

_DERIVE_TAILS = {"split", "fold_in", "PRNGKey", "key"}
_KEY_PARAM_NAMES = {"key", "rng", "rng_key", "prng_key", "subkey"}


def _is_random_derive(call: ast.Call) -> Optional[str]:
    head = dotted(call.func)
    if not head:
        return None
    tail = tail_of(head)
    if tail in _DERIVE_TAILS and ("random" in head
                                  or head in ("split", "fold_in",
                                              "PRNGKey")):
        return tail
    if tail in ("_next_key", "next_key"):
        return "next_key"
    return None


class _FnAnalysis:
    """Order-aware single-function key-lifetime analysis.

    Walks the statement list linearly; branches of an if/else are
    analyzed independently against a snapshot and merged by max-use;
    loop bodies are walked twice to model re-entry (a key defined
    outside a loop and consumed inside it without a rebinding is a
    reuse on iteration 2)."""

    def __init__(self, fn_node, ctx: FileContext, qual: str):
        self.fn = fn_node
        self.ctx = ctx
        self.qual = qual
        self.findings: List[Finding] = []
        # var -> (generation id, use count for current generation)
        self.uses: Dict[str, int] = {}
        self.first_use_line: Dict[str, int] = {}
        # FC402 tracking: derived-var -> assign lineno, consumed?
        self.derived_at: Dict[str, int] = {}
        self.derived_used: Set[str] = set()

    # -- key-var bookkeeping -------------------------------------------
    def _rebind(self, names):
        for n in names:
            self.uses[n] = 0

    def _is_key_var(self, name: str) -> bool:
        return name in self.uses

    def run(self):
        # seed: parameters with key-ish names are keys
        args = self.fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            nm = a.arg
            if nm in _KEY_PARAM_NAMES or nm.endswith("_key") or \
                    nm.endswith("_rng"):
                self.uses[nm] = 0
        self._walk(self.fn.body, loop_depth=0)
        # closure consumption: a nested def reading the key counts as a
        # use for FC402 (e.g. a weight-loader closure folding a base key)
        for sub in ast.walk(self.fn):
            if isinstance(sub, FuncNode) and sub is not self.fn:
                for nm in ast.walk(sub):
                    if isinstance(nm, ast.Name) and \
                            isinstance(nm.ctx, ast.Load):
                        self.derived_used.add(nm.id)
        # FC402: derived keys never consumed
        for name, line in self.derived_at.items():
            if name not in self.derived_used and \
                    not name.startswith("_"):
                self.findings.append(Finding(
                    self.ctx.path, line, "FC402",
                    f"PRNG derivation result '{name}' is never "
                    f"consumed — the old key likely kept being used",
                    self.qual))
        return self.findings

    # -- statement walking ---------------------------------------------
    def _walk(self, stmts, loop_depth: int):
        for st in stmts:
            self._stmt(st, loop_depth)

    def _stmt(self, st, loop_depth: int):
        if isinstance(st, FuncNode + (ast.ClassDef,)):
            return  # separate scope
        if isinstance(st, ast.Assign):
            self._consume_in(st.value, loop_depth)
            self._handle_assign(st.targets, st.value)
        elif isinstance(st, ast.AugAssign):
            self._consume_in(st.value, loop_depth)
        elif isinstance(st, ast.Expr):
            # bare-expression derivation = dead result
            call = st.value if isinstance(st.value, ast.Call) else None
            if call is not None:
                kind = _is_random_derive(call)
                if kind in ("split", "fold_in"):
                    self.findings.append(Finding(
                        self.ctx.path, st.lineno, "FC402",
                        f"`{dotted(call.func)}(...)` result discarded "
                        f"— a split/fold_in that nobody consumes is "
                        f"dead entropy", self.qual))
            self._consume_in(st.value, loop_depth)
        elif isinstance(st, (ast.If,)):
            self._consume_in(st.test, loop_depth)
            snap = dict(self.uses)
            self._walk(st.body, loop_depth)
            after_then = self.uses
            self.uses = dict(snap)
            self._walk(st.orelse, loop_depth)
            after_else = self.uses
            # a branch that cannot fall through (return/raise/continue/
            # break) contributes nothing to the post-If state — its key
            # consumptions never meet the code below the If
            if _terminates(st.body):
                after_then = snap
            if _terminates(st.orelse):
                after_else = snap
            merged = {}
            for k in set(after_then) | set(after_else):
                merged[k] = max(after_then.get(k, 0),
                                after_else.get(k, 0))
            self.uses = merged
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._consume_in(st.iter, loop_depth)
            self._rebind(n for n in _target_names(st.target)
                         if self._is_key_var(n))
            self._walk(st.body, loop_depth + 1)
            self._walk(st.orelse, loop_depth)
        elif isinstance(st, ast.While):
            self._consume_in(st.test, loop_depth)
            self._walk(st.body, loop_depth + 1)
            self._walk(st.orelse, loop_depth)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._consume_in(item.context_expr, loop_depth)
            self._walk(st.body, loop_depth)
        elif isinstance(st, ast.Try):
            self._walk(st.body, loop_depth)
            for h in st.handlers:
                self._walk(h.body, loop_depth)
            self._walk(st.orelse, loop_depth)
            self._walk(st.finalbody, loop_depth)
        elif isinstance(st, ast.Return) and st.value is not None:
            # returning a key hands ownership out — not a consumption
            for name in _names_in(st.value):
                self.derived_used.add(name)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._consume_in(child, loop_depth)

    def _handle_assign(self, targets, value):
        # any read of a derived key (aliasing, container store) counts
        # as "used" for FC402 — only NEVER-read derivations are dead
        for nm in _names_in(value):
            self.derived_used.add(nm)
        names = []
        for t in targets:
            names.extend(_target_names(t))
        derive = _is_random_derive(value) \
            if isinstance(value, ast.Call) else None
        if derive:
            # key(s) freshly derived: every target becomes a gen-0 key
            self._rebind(names)
            for n in names:
                self.derived_at.setdefault(n, value.lineno)
            return
        # subscript of a key collection (keys[i]) is also a key
        if isinstance(value, ast.Subscript):
            base = value.value
            if isinstance(base, ast.Name) and self._is_key_var(base.id):
                self._rebind(names)
                return
        # plain rebinding kills key-ness of the target (it now holds
        # something else); aliasing `k2 = key` copies the generation
        if isinstance(value, ast.Name) and self._is_key_var(value.id):
            for n in names:
                self.uses[n] = self.uses.get(value.id, 0)
            return
        for n in names:
            self.uses.pop(n, None)

    def _consume_in(self, expr, loop_depth: int):
        """Find key-variable consumptions inside an expression: the key
        appearing as an ARGUMENT of a call that plausibly consumes
        entropy (jax.random.* including split itself, compiled `*_j` /
        `*_impl` dispatches, the op-apply machinery). Passing a key to a
        metadata-only helper (shape snapshot, logging) is not counted —
        precision over recall."""
        if expr is None:
            return
        # ANY read of a derived key (zip iteration, container build,
        # non-consuming helper) counts as "used" for FC402
        for nm in _names_in(expr):
            self.derived_used.add(nm)
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            if not _is_consuming_call(sub):
                continue
            derive = _is_random_derive(sub)
            arg_names = []
            for a in sub.args:
                if isinstance(a, ast.Name):
                    arg_names.append(a.id)
            for kw in sub.keywords:
                if isinstance(kw.value, ast.Name):
                    arg_names.append(kw.value.id)
            if derive in ("fold_in", "next_key"):
                # fold_in derives an INDEPENDENT stream from the base
                # key (the canonical per-step idiom: `k = fold_in(key,
                # i)` each iteration) — it does not consume the base;
                # only using the base in a SAMPLER (or after split)
                # correlates streams. Mark reads for FC402 and move on.
                for nm in arg_names:
                    if self._is_key_var(nm):
                        self.derived_used.add(nm)
                continue
            for nm in arg_names:
                if not self._is_key_var(nm):
                    continue
                self.derived_used.add(nm)
                count = self.uses.get(nm, 0) + 1
                # inside a loop, a consumption of a key whose current
                # generation was minted OUTSIDE the loop repeats every
                # iteration — model by counting it twice
                if loop_depth > 0 and not self._assigned_in_loop(nm, sub):
                    count += 1
                self.uses[nm] = count
                if count >= 2:
                    self.findings.append(Finding(
                        self.ctx.path, sub.lineno, "FC401",
                        f"PRNG key '{nm}' consumed again without an "
                        f"intervening split — correlated randomness "
                        f"(split the key per consumption)", self.qual))
                    self.uses[nm] = -10**6  # report once per generation

    def _assigned_in_loop(self, name: str, use_site) -> bool:
        """Is `name` (re)assigned anywhere inside the innermost loop
        containing use_site? Approximation: assigned inside ANY loop in
        this function."""
        for sub in ast.walk(self.fn):
            if isinstance(sub, (ast.For, ast.While, ast.AsyncFor)):
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Assign):
                        for t in inner.targets:
                            if name in _target_names(t):
                                return True
                    if isinstance(inner, (ast.For, ast.AsyncFor)) and \
                            inner is not sub:
                        if name in _target_names(inner.target):
                            return True
        return False


def _is_consuming_call(call: ast.Call) -> bool:
    if _is_random_derive(call):
        return True
    head = dotted(call.func) or ""
    tail = tail_of(head) or ""
    if "random" in head:
        return True
    if tail.endswith(("_j", "_impl", "_fn")):
        return True
    return tail in ("apply", "apply_nodiff", "sample", "categorical")


def _terminates(stmts) -> bool:
    """Whether a branch body always leaves the enclosing suite."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _target_names(t) -> List[str]:
    out = []
    for sub in ast.walk(t):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
    return out


def _names_in(expr) -> List[str]:
    return [n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]


def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    owner_of = func_of_map(tree)
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            qual = owner_of.get(node.body[0] if node.body else node,
                                node.name)
            findings.extend(_FnAnalysis(node, ctx, qual).run())
    return findings


def setup(register):
    register("prng", check, {
        "FC401": "PRNG key consumed twice without an intervening split",
        "FC402": "split/fold_in derivation whose result is never used",
    })
