"""On-disk findings cache so the tier-1 gate doesn't re-parse the whole
tree every run.

Safety model — the cache can never serve a stale verdict, only miss:
- one entry per repo-relative PATH (and rules filter), carrying the
  sha256 of the FILE CONTENT it was computed from: an edit — including
  adding/removing a suppression comment — misses and supersedes the
  entry in place, so the file stays bounded by tree size; two identical
  files cache separately, since findings and baseline keys are
  path-addressed;
- the whole cache is versioned by a sha256 over the flightcheck package
  sources AND the canonical SpecLayout table
  (paddle_tpu/distributed/spec_layout.py, an FC605 input), so changing
  any checker — or the table — invalidates everything;
- the rules filter participates in the key (a ``--rules FC6`` run and a
  full run cache separately).

Findings are stored post-suppression (exactly what check_source
returned). The file lives next to the package
(``tools/flightcheck/.findings_cache.json``) and is git-ignored.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence

from .core import Finding

DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".findings_cache.json")

_FIELDS = ("path", "line", "rule", "message", "func", "chain")

_version: Optional[str] = None


def checker_version() -> str:
    """sha256 over the package's own .py sources plus every out-of-tree
    checker INPUT (the canonical SpecLayout table FC605 parses) — any
    rule or table change flushes the cache."""
    global _version
    if _version is None:
        from .core import _REPO_ROOT
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.abspath(__file__))
        extra = [os.path.join(_REPO_ROOT, "paddle_tpu", "distributed",
                              "spec_layout.py")]
        paths = [os.path.join(pkg, fn) for fn in sorted(os.listdir(pkg))
                 if fn.endswith(".py")] + extra
        for path in paths:
            try:
                with open(path, "rb") as fh:
                    h.update(os.path.basename(path).encode())
                    h.update(fh.read())
            except OSError:
                h.update(f"missing:{path}".encode())
        _version = h.hexdigest()[:16]
    return _version


def _key(rules: Optional[Sequence[str]], path: str) -> str:
    rk = ",".join(sorted(rules)) if rules else "*"
    return path + "::" + rk


def _sha(source: str) -> str:
    return hashlib.sha256(source.encode()).hexdigest()[:32]


class FindingsCache:
    def __init__(self, path: str = DEFAULT_CACHE):
        self.path = path
        self._dirty = False
        self._entries: Dict[str, List[dict]] = {}
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
            if data.get("version") == checker_version():
                self._entries = data.get("entries", {})
        except (OSError, ValueError):
            pass

    def lookup(self, source: str,
               rules: Optional[Sequence[str]] = None,
               path: str = "") -> Optional[List[Finding]]:
        # one entry per (path, rules); the content hash lives INSIDE the
        # value, so edits supersede in place and the file stays bounded
        # by the number of files, not the number of edits
        entry = self._entries.get(_key(rules, path))
        if not isinstance(entry, dict) or \
                entry.get("sha") != _sha(source):
            return None
        try:
            return [Finding(**{k: r[k] for k in _FIELDS})
                    for r in entry.get("findings", [])]
        except (KeyError, TypeError):
            return None

    def store(self, source: str, rules: Optional[Sequence[str]],
              findings: List[Finding], path: str = ""):
        self._entries[_key(rules, path)] = {
            "sha": _sha(source),
            "findings": [{k: getattr(f, k) for k in _FIELDS}
                         for f in findings]}
        self._dirty = True

    def save(self):
        if not self._dirty:
            return
        payload = {"version": checker_version(),
                   "entries": self._entries}
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(self.path) or ".",
                prefix=".findings_cache.")
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
            tmp = None
            self._dirty = False
        except OSError:
            pass
        finally:
            if tmp is not None:     # failed write: no orphaned temp
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
