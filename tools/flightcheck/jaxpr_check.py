"""jaxpr-backed cross-check: trace the real serving/paged-decode entry
points and verify the AST verdicts against ground truth.

The AST pass is syntactic — it cannot prove a flagged branch is really
reached with a tracer, and it cannot see hazards hidden behind dynamic
dispatch. This mode closes both gaps for the code that matters most
(the serving hot path):

1. it builds a TINY PagedLlamaDecoder + ServingEngine on CPU and runs
   ``jax.make_jaxpr`` (under ``jax.checking_leaks``) over every compiled
   entry point — the decoder ``*_impl`` methods and the engine's jitted
   prefill/decode closures. Abstract tracing executes nothing but takes
   exactly the code paths jit takes: a genuine tracer-safety bug
   (FC101-FC103) raises a ConcretizationTypeError / TracerArrayConversion
   right here, and a leaked tracer trips the leak checker. A trace
   FAILURE is reported as a confirmed hazard even if the AST pass missed
   it.
2. any AST tracer-safety finding located inside a function that traced
   CLEANLY is downgraded to "refuted by jaxpr" — the cross-check that
   keeps the AST pass low-false-positive.
3. the produced jaxprs get an independent PRNG audit: a key variable
   feeding two separate ``threefry``/``random_*`` equations without an
   intervening derivation is FC401 at the IR level, immune to AST-level
   aliasing blind spots.

Used by ``python -m tools.flightcheck --jaxpr`` and by the tier-1 test
(tests/test_flightcheck.py::TestJaxprCrossCheck).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_tiny():
    """Smallest engine that exercises every compiled serving program."""
    import numpy as np
    from paddle_tpu.models.llama import llama_tiny
    from paddle_tpu.inference.paged_decode import PagedLlamaDecoder
    from paddle_tpu.inference.serving import ServingEngine

    cfg = llama_tiny(num_hidden_layers=2, hidden_size=32,
                     intermediate_size=64, num_attention_heads=4,
                     num_key_value_heads=2, vocab_size=64,
                     max_position_embeddings=64)
    dec = PagedLlamaDecoder.from_config(cfg, num_blocks=16, block_size=4)
    # spec_decode forces ragged=True on top of the dense programs, and
    # the lora registry (ISSUE 10) adds the multi-tenant program
    # family, so one engine carries every compiled serving program —
    # the dense per-phase set, the ragged [T, W] chunk, the ISSUE-9
    # speculative verify program and the lora twins
    from paddle_tpu.inference.lora import AdapterRegistry
    from paddle_tpu.inference.spec_decode import SpecConfig
    reg = AdapterRegistry(rank=2)
    reg.register_random("tenant0", seed=0)
    eng = ServingEngine(dec, max_batch_size=2, prompt_buckets=(8, 16),
                        chunk_size=2, prefill_chunk=8,
                        spec_decode=SpecConfig(draft_len=2), lora=reg)
    return dec, eng


def trace_entry_points() -> Dict[Tuple[str, str], str]:
    """{(file-suffix, func-name): "ok" | "error: ..."} for every entry
    point. Tracing is abstract (make_jaxpr) — no compile, no execution."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    results: Dict[Tuple[str, str], str] = {}
    dec, eng = _build_tiny()
    cache = dec.cache
    serving = "paddle_tpu/inference/serving.py"
    paged = "paddle_tpu/inference/paged_decode.py"

    b, s, mp_, vocab = 2, 8, dec.max_pages, dec.cfg.vocab_size
    ids = jnp.zeros((b, s), jnp.int32)
    slots = jnp.zeros((b, s), jnp.int32)
    last_idx = jnp.full((b,), s - 1, jnp.int32)
    ncv = jnp.zeros((b,), jnp.int32)
    ptab = jnp.zeros((b, eng._prefix_pages), jnp.int32)
    temps = jnp.zeros((b,), jnp.float32)
    top_ks = jnp.zeros((b,), jnp.int32)
    top_ps = jnp.ones((b,), jnp.float32)
    reps = jnp.ones((b,), jnp.float32)
    seen = jnp.zeros((b, vocab), bool)
    allowed = jnp.ones((b, vocab), bool)
    key = jax.random.PRNGKey(0)
    T = eng.chunk
    tables_all = jnp.zeros((T, eng.max_b, mp_), jnp.int32)
    ctx_all = jnp.zeros((T, eng.max_b), jnp.int32)
    slots_all = jnp.zeros((T, eng.max_b), jnp.int32)
    first_ids = jnp.zeros((eng.max_b,), jnp.int32)
    temps_mb = jnp.zeros((eng.max_b,), jnp.float32)
    keys_all = jax.random.split(key, T)
    seen_mb = jnp.zeros((eng.max_b, vocab), bool)
    allowed_mb = jnp.ones((eng.max_b, vocab), bool)

    entries = [
        (paged, "_prefill_impl",
         lambda: (dec._prefill_impl, (dec.weights, cache.k, cache.v,
                                      ids, slots, last_idx))),
        (paged, "_prefill_prefix_impl",
         lambda: (dec._prefill_prefix_impl,
                  (dec.weights, cache.k, cache.v, ids, slots, last_idx,
                   ncv, ptab))),
        (paged, "_prefill_chunk_impl",
         lambda: (dec._prefill_chunk_impl,
                  (dec.weights, cache.k, cache.v, ids[:1], slots[:1],
                   ncv[:1], ptab[:1]))),
        (paged, "_decode_logits",
         lambda: (dec._decode_logits,
                  (dec.weights, cache.k, cache.v, first_ids[:b],
                   tables_all[0, :b], ctx_all[0, :b], slots_all[0, :b]))),
        (serving, "prefill",
         lambda: (eng._prefill_j, (dec.weights, cache.k, cache.v, ids,
                                   slots, last_idx, temps, key, top_ks,
                                   top_ps, reps, seen, allowed))),
        (serving, "prefill_prefix",
         lambda: (eng._prefill_prefix_j,
                  (dec.weights, cache.k, cache.v, ids, slots, last_idx,
                   ncv, ptab, temps, key, top_ks, top_ps, reps, seen,
                   allowed))),
        (serving, "decode_chunk",
         lambda: (eng._decode_j, (dec.weights, cache.k, cache.v,
                                  first_ids, tables_all, ctx_all,
                                  slots_all, temps_mb, keys_all))),
        (serving, "decode_chunk_rich",
         lambda: (eng._decode_rich_j,
                  (dec.weights, cache.k, cache.v, first_ids, tables_all,
                   ctx_all, slots_all, temps_mb, keys_all,
                   jnp.zeros((eng.max_b,), jnp.int32),
                   jnp.ones((eng.max_b,), jnp.float32),
                   jnp.ones((eng.max_b,), jnp.float32), seen_mb,
                   allowed_mb))),
        (serving, "merge_first",
         lambda: (eng._merge_first_j,
                  (jnp.zeros((eng.max_b, T), jnp.int32),
                   jnp.zeros((eng.max_b,), jnp.int32),
                   jnp.zeros((eng.max_b,), jnp.int32),
                   jnp.zeros((eng.max_b,), bool)))),
    ]
    if eng.prefill_chunk:
        c = eng.prefill_chunk
        entries.append(
            (serving, "prefill_mid",
             lambda: (eng._prefill_mid_j,
                      (dec.weights, cache.k, cache.v,
                       jnp.zeros((1, c), jnp.int32),
                       jnp.zeros((1, c), jnp.int32),
                       jnp.zeros((1,), jnp.int32),
                       jnp.zeros((1, 1), jnp.int32)))))
    if eng.spec is not None:
        w = 4
        entries.append(
            (serving, "spec_chunk",
             lambda: (eng._spec_j,
                      (dec.weights, cache.k, cache.v,
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((w,), bool),
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((w,), jnp.int32),
                       jnp.zeros((eng.max_b + 1, mp_), jnp.int32),
                       jnp.zeros((w,), jnp.float32), key,
                       jnp.arange(w, dtype=jnp.int32),
                       jnp.zeros((w,), bool)))))
    if eng.lora is not None:
        # the ISSUE-10 multi-tenant ragged program: lora-pool gather +
        # per-row adapter deltas wrapped around the same [T, W] scan
        wl = 4
        n_pages = eng.lora.layout.n_pages
        entries.append(
            (serving, "ragged_lora_chunk",
             lambda: (eng._ragged_lora_j,
                      (dec.weights, cache.k, cache.v, cache.lora_pool,
                       jnp.zeros((1,), jnp.int32),
                       jnp.zeros((eng.max_b + 1, n_pages), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((wl,), jnp.int32),
                       jnp.zeros((wl,), jnp.int32),
                       jnp.zeros((wl,), bool),
                       jnp.zeros((wl,), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((T, wl), jnp.int32),
                       jnp.zeros((T, wl), bool),
                       jnp.zeros((eng.max_b + 1, mp_), jnp.int32),
                       jnp.zeros((T, wl), jnp.float32), keys_all))))

    jaxprs = {}
    for file_sfx, name, build in entries:
        try:
            fn, args = build()
            with jax.checking_leaks():
                jaxpr = jax.make_jaxpr(fn)(*args)
            jaxprs[(file_sfx, name)] = jaxpr
            results[(file_sfx, name)] = "ok"
        except Exception as e:  # trace failure IS the finding
            results[(file_sfx, name)] = \
                f"error: {type(e).__name__}: {str(e)[:200]}"
    results["__jaxprs__"] = jaxprs   # side-channel for the PRNG audit
    return results


def audit_prng(jaxpr) -> List[str]:
    """IR-level FC401: variables feeding >1 random-consuming equation.
    Returns human-readable descriptions (empty = clean)."""
    from collections import Counter

    counts: Counter = Counter()

    def is_key_var(v) -> bool:
        aval = getattr(v, "aval", None)
        return aval is not None and "key" in str(aval.dtype)

    seen_jx = set()

    def walk(jx):
        if id(jx) in seen_jx:   # shared sub-jaxprs walk once — a var
            return              # is consumed per REFERENCE, not per print
        seen_jx.add(id(jx))
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            for v in eqn.invars:
                if hasattr(v, "val"):              # literal
                    continue
                # a typed PRNG key consumed by any equation, or a raw
                # uint32 key entering random_wrap — each counts once; a
                # correct program consumes every key var exactly once
                if is_key_var(v) or (prim == "random_wrap"):
                    counts[(id(jx), v)] += 1
            for sub in eqn.params.values():
                core = getattr(sub, "jaxpr", None)
                if core is not None:
                    walk(core)
                elif isinstance(sub, (list, tuple)):
                    for s in sub:
                        core = getattr(s, "jaxpr", None)
                        if core is not None:
                            walk(core)

    walk(jaxpr.jaxpr)
    return [f"key var {v} consumed by {n} random equations"
            for (_, v), n in sorted(counts.items(), key=str) if n > 1]


@dataclass
class Report:
    traced: Dict[Tuple[str, str], str] = field(default_factory=dict)
    trace_failures: List[str] = field(default_factory=list)
    refuted: List = field(default_factory=list)
    confirmed: List = field(default_factory=list)
    prng_notes: List[str] = field(default_factory=list)

    def summary(self) -> str:
        n_ok = sum(1 for v in self.traced.values() if v == "ok")
        lines = [f"jaxpr cross-check: {n_ok}/{len(self.traced)} entry "
                 f"points traced clean"]
        for msg in self.trace_failures:
            lines.append(f"  TRACE FAILURE: {msg}")
        for f in self.refuted:
            lines.append(f"  refuted by jaxpr (function traced clean): "
                         f"{f.path}:{f.line} {f.rule}")
        for n in self.prng_notes:
            lines.append(f"  PRNG audit: {n}")
        if not self.trace_failures and not self.prng_notes:
            lines.append("  AST verdicts agree with the traced jaxprs")
        return "\n".join(lines)


def cross_check(findings) -> Report:
    """Verify AST findings against the traced entry points. Tracer-
    safety findings (FC101-103) inside functions that traced clean are
    refuted; trace failures surface as new confirmed hazards."""
    rep = Report()
    results = trace_entry_points()
    jaxprs = results.pop("__jaxprs__", {})
    rep.traced = results
    for (file_sfx, name), status in results.items():
        if status != "ok":
            rep.trace_failures.append(f"{file_sfx}::{name}: {status}")
    for key, jx in jaxprs.items():
        for note in audit_prng(jx):
            rep.prng_notes.append(f"{key[0]}::{key[1]}: {note}")
    ok_funcs = {(f, n) for (f, n), st in results.items() if st == "ok"}
    for f in findings:
        if f.rule in ("FC101", "FC102", "FC103") and any(
                f.path.endswith(file_sfx) and
                (f.func or "").split(".")[-1] == name
                for file_sfx, name in ok_funcs):
            rep.refuted.append(f)
        else:
            rep.confirmed.append(f)
    return rep
