"""Recompilation-hazard rules (FC201/FC202): jit cache blowups.

Hazard: ``jax.jit`` retraces whenever a static input changes and
recompiles whenever traced input SHAPES change. Two syntactic patterns
account for most cache blowups in practice:

- a jitted callee uses a (non-static) Python argument as a shape or a
  Python loop bound — ``range(n)``, ``jnp.zeros(n)``, ``x.reshape(n,
  -1)``, ``lax.scan(..., length=n)``. If ``n`` arrives as a tracer the
  trace fails; if callers "fix" that by passing plain ints, every new
  value silently compiles a fresh program. The argument must either be
  declared in ``static_argnums`` (capping the variant count by design)
  or become a traced operand. Real example from this tree: the serving
  engine buckets prompt lengths (``serving.py prompt_buckets``) exactly
  so the jitted prefill sees a CAPPED set of static shapes — FC201
  polices the uncapped version of that mistake.
- ``jax.jit(...)`` called inside a ``for``/``while`` body mints a fresh
  compiled callable (and cache entry) per iteration; hoist it or cache
  it (cf. ``ServingEngine.__init__`` jitting once and reusing across
  every step).
- a kernel closure captures a per-call PRNG key instead of taking it as
  an argument. This repo's compiled-segment cache
  (``jit/partial_capture.py``) fingerprints closures BY CELL CONTENTS
  (``_fp_fn`` → ``_fp_const`` → ``np.asarray(key).tobytes()``), so a
  freshly-split key baked into a closure changes the fingerprint every
  call: guaranteed cache miss, full retrace + recompile per call, plus
  a host transfer inside the fingerprint itself. The repo's own
  ``nn.functional.dropout`` documents the correct idiom — "key passes
  as a positional arg (not a closure cell) so partial capture lifts it
  into a segment input — stochastic segments stay cache-hittable
  across calls". Real examples fixed under this rule: ``rrelu`` /
  ``gumbel_softmax`` (nn/functional/activation.py), ``alpha_dropout``
  / ``class_center_sample`` (nn/functional/common.py), ``bernoulli`` /
  ``multinomial`` / ``poisson`` / ``binomial`` / ``standard_gamma``
  (tensor/random.py).

Rules:
- FC201: non-static parameter of a jitted function used in a Python
  shape/loop-bound position.
- FC202: jit wrapping inside a loop body.
- FC203: per-call PRNG key captured in a kernel closure instead of
  passed as an argument.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, FileContext
from .scopes import (FuncNode, call_head, dotted, find_traced_scopes,
                     func_of_map, tail_of, unwrap_partial, value_uses)

# call tails whose FIRST positional argument is a shape / count
_SHAPE_CALL_TAILS = {"zeros", "ones", "full", "empty", "arange",
                     "broadcast_to", "tile", "eye", "range"}
_LENGTH_KWARGS = {"length", "num", "axis_size", "shape", "total_repeat_length"}


def _shape_position_uses(fn_node, params: Set[str]):
    """Yield (param, call_node, desc) for params used where Python needs
    a concrete int: range()/creation shapes/reshape args/scan length."""
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Call):
            continue
        head = dotted(sub.func)
        tail = tail_of(head)
        cands = []
        if tail in _SHAPE_CALL_TAILS:
            if sub.args:
                cands.append((sub.args[0], f"`{tail}()` shape/bound"))
        if isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in ("reshape", "broadcast_to", "resize"):
            for a in sub.args:
                cands.append((a, f"`.{sub.func.attr}()` target shape"))
        for kw in sub.keywords:
            if kw.arg in _LENGTH_KWARGS:
                cands.append((kw.value, f"`{kw.arg}=` of `{tail}()`"))
        for expr, desc in cands:
            # value_uses skips x.shape / len(x) — sizing a buffer from
            # traced METADATA is fine; sizing from the VALUE is not
            for nm in value_uses(expr, params):
                yield nm.id, sub, desc


def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    owner_of = func_of_map(tree)

    # ---- FC201: shape-position use of a non-static jit param ----------
    for scope in find_traced_scopes(tree):
        if "jit" not in scope.reason:
            continue
        node = scope.node
        if isinstance(node, ast.Lambda):
            continue
        params = set(scope.traced_params())
        if not params:
            continue
        seen = set()
        for pname, call, desc in _shape_position_uses(node, params):
            key = (pname, call.lineno)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                ctx.path, call.lineno, "FC201",
                f"jitted callee '{scope.qualname}' uses arg '{pname}' "
                f"as {desc}: a traced value cannot size a Python "
                f"shape, and an un-static python int recompiles per "
                f"value — add it to static_argnums or bucket it",
                owner_of.get(call, scope.qualname)))

    # ---- FC203: per-call PRNG key captured by an escaping closure -----
    from .prng import _is_random_derive
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, FuncNode)]:
        key_vars: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    _is_random_derive(sub.value):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        key_vars.add(t.id)
        if not key_vars:
            continue
        # names of nested defs that are handed to the COMPILED machinery
        # (eager-only escapes — constructors, plain helpers — don't hit
        # the segment cache and are fine to close over a key)
        compiled_sinks = {"apply", "apply_nodiff", "jit", "pjit",
                          "DecompAware", "checkpoint", "remat"}
        escaping_names: Set[str] = set()
        escaping_lambdas = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and \
                    tail_of(dotted(sub.func)) in compiled_sinks:
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    if isinstance(a, ast.Name):
                        escaping_names.add(a.id)
                    elif isinstance(a, ast.Lambda):
                        escaping_lambdas.append(a)
        for nested in ast.walk(fn):
            is_lambda = isinstance(nested, ast.Lambda)
            if not (is_lambda or
                    (isinstance(nested, FuncNode) and nested is not fn)):
                continue
            if is_lambda:
                if nested not in escaping_lambdas:
                    continue
                bound = {a.arg for a in nested.args.args}
                body_nodes = ast.walk(nested.body)
            else:
                if nested.name not in escaping_names:
                    continue
                bound = {a.arg for a in nested.args.args}
                for s in ast.walk(nested):
                    if isinstance(s, (ast.Assign, ast.For)):
                        for t in (s.targets
                                  if isinstance(s, ast.Assign)
                                  else [s.target]):
                            for nm in ast.walk(t):
                                if isinstance(nm, ast.Name):
                                    bound.add(nm.id)
                body_nodes = ast.walk(nested)
            captured = sorted({
                nm.id for nm in body_nodes
                if isinstance(nm, ast.Name)
                and isinstance(nm.ctx, ast.Load)
                and nm.id in key_vars and nm.id not in bound})
            if captured:
                findings.append(Finding(
                    ctx.path, nested.lineno, "FC203",
                    f"kernel closure captures per-call PRNG key "
                    f"'{captured[0]}' — the segment cache fingerprints "
                    f"closure cells by content, so every call retraces "
                    f"and recompiles; pass the key as a positional "
                    f"argument instead (see nn.functional.dropout)",
                    owner_of.get(nested, "")))

    # ---- FC202: jit() inside a loop body ------------------------------
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
    flagged: Set[int] = set()
    for loop in loops:
        # the accepted memoization idiom is exempt: the jit result is
        # stored into a cache subscript (`cache[key] = jfn`) in the
        # same loop, so iterations after the first reuse the callable
        memoized: Set[str] = set()
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Name):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript):
                        memoized.add(sub.value.id)
        for sub in ast.walk(loop):
            if sub in (loop,) or not isinstance(sub, ast.Call):
                continue
            head = tail_of(call_head(sub))
            is_jit = head in ("jit", "pjit")
            if not is_jit:
                inner = unwrap_partial(sub)
                is_jit = inner is not None and \
                    tail_of(call_head(inner)) in ("jit", "pjit")
            if not is_jit or sub.lineno in flagged:
                continue
            parent_assign = next(
                (a for a in ast.walk(loop) if isinstance(a, ast.Assign)
                 and any(s is sub for s in ast.walk(a.value))), None)
            if parent_assign is not None and any(
                    isinstance(t, ast.Name) and t.id in memoized
                    for t in parent_assign.targets):
                continue
            flagged.add(sub.lineno)
            findings.append(Finding(
                ctx.path, sub.lineno, "FC202",
                "jax.jit called inside a loop body creates a fresh "
                "compiled callable (and cache entry) every "
                "iteration; hoist the jit out of the loop or cache "
                "the wrapped callable",
                owner_of.get(sub, "")))
    return findings


def setup(register):
    register("recompile", check, {
        "FC201": "non-static jit arg used as a Python shape/loop bound",
        "FC202": "jax.jit wrapped inside a loop body",
        "FC203": "per-call PRNG key captured in a kernel closure",
    })
