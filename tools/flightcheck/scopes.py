"""Shared AST machinery: traced-scope discovery and value-taint walking.

"Traced scope" = a function whose body runs under a JAX trace — the
region where Python control flow on array values silently goes wrong.
We find them syntactically: ``@jax.jit``-style decorations (including
``partial(jax.jit, ...)``), functions passed by name (or inline lambda)
into jit/grad/vmap/scan/cond/shard_map-style higher-order entry points,
and any ``def`` nested inside one of those (its arguments bind tracers
when the enclosing trace calls it).

"Value use" = an expression position where the runtime VALUE of an
array flows into Python — as opposed to static metadata. ``x.shape``,
``x.dtype``, ``x.ndim``, ``len(x)``, ``isinstance(x, ...)`` and
``x is None`` are static under tracing and never count; ``x + 1``,
``x[i]``, ``x > 0`` do.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

# attributes of a traced array that are Python-static during tracing
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding",
                "weak_type", "itemsize", "nbytes"}

# call heads whose RESULT is static even on a traced argument
STATIC_CALLS = {"len", "isinstance", "type", "id", "repr", "getattr",
                "hasattr", "callable"}

# higher-order entry points that trace their function argument(s).
# matched on the dotted tail, so jax.lax.scan / lax.scan / plain scan
# via `from jax.lax import scan` all hit.
TRACING_HOF_TAILS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "linearize", "vjp", "jvp", "checkpoint",
    "remat", "custom_vjp", "custom_jvp", "scan", "cond", "while_loop",
    "fori_loop", "switch", "associative_scan", "shard_map", "eval_shape",
    "make_jaxpr", "named_call", "map",
    # Pallas kernel bodies run under the Pallas trace: Python control
    # flow on Ref VALUES (vs static shapes/program_ids) is the same
    # hazard class as under jit
    "pallas_call",
}

JIT_TAILS = {"jit", "pjit"}


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_head(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def tail_of(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def unwrap_partial(call: ast.Call) -> Optional[ast.Call]:
    """partial(jax.jit, **kw) -> synthetic view of the inner jit call
    (returns the call node whose head is the partial'd function)."""
    head = tail_of(call_head(call))
    if head == "partial" and call.args:
        inner = call.args[0]
        inner_name = dotted(inner)
        if inner_name and tail_of(inner_name) in TRACING_HOF_TAILS:
            fake = ast.Call(func=inner, args=list(call.args[1:]),
                            keywords=list(call.keywords))
            return fake
    return None


def is_tracing_call(call: ast.Call) -> Optional[str]:
    """Return the HOF tail name if this call traces a function arg."""
    head = tail_of(call_head(call))
    if head in TRACING_HOF_TAILS:
        return head
    inner = unwrap_partial(call)
    if inner is not None:
        return tail_of(call_head(inner))
    return None


def literal_int_collection(node: ast.AST) -> Optional[List]:
    """Constant / tuple/list of constants -> python value, else None."""
    try:
        val = ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None
    if isinstance(val, (int, str)):
        return [val]
    if isinstance(val, (tuple, list, set)):
        return list(val)
    return None


def static_arg_info(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """static_argnums / static_argnames of a jit(...) call node."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnum"):
            vals = literal_int_collection(kw.value) or []
            nums.update(v for v in vals if isinstance(v, int))
        elif kw.arg == "static_argnames":
            vals = literal_int_collection(kw.value) or []
            names.update(v for v in vals if isinstance(v, str))
    return nums, names


FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class TracedScope:
    """One function body believed to run under a JAX trace."""

    def __init__(self, node, qualname: str, reason: str,
                 static_nums: Set[int] = frozenset(),
                 static_names: Set[str] = frozenset()):
        self.node = node
        self.qualname = qualname
        self.reason = reason          # "jit-decorator" / "scan-callee"...
        self.static_nums = set(static_nums)
        self.static_names = set(static_names)

    def traced_params(self) -> List[str]:
        node = self.node
        if isinstance(node, ast.Lambda):
            args = node.args
        else:
            args = node.args
        params = [a.arg for a in args.posonlyargs + args.args]
        # a jitted BOUND method (`jax.jit(self._impl)`) counts argnums
        # from the first non-self parameter
        off = 1 if params and params[0] in ("self", "cls") else 0
        out = []
        for i, p in enumerate(params):
            if p in ("self", "cls"):
                continue
            if (i - off) in self.static_nums or p in self.static_names:
                continue
            out.append(p)
        out.extend(a.arg for a in args.kwonlyargs
                   if a.arg not in self.static_names)
        return out


def _qualname_map(tree: ast.Module) -> Dict[ast.AST, str]:
    """def/lambda node -> dotted qualname."""
    out: Dict[ast.AST, str] = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FuncNode):
                q = f"{prefix}{child.name}"
                out[child] = q
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def find_traced_scopes(tree: ast.Module) -> List[TracedScope]:
    qnames = _qualname_map(tree)
    scopes: Dict[ast.AST, TracedScope] = {}

    # method name -> def node per class, so `jax.jit(self._prefill_impl)`
    # in __init__ resolves to the class's method
    methods_of_class: Dict[ast.AST, Dict[str, ast.AST]] = {}
    owner_class: Dict[ast.AST, ast.AST] = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            meths = {n.name: n for n in cls.body
                     if isinstance(n, FuncNode)}
            methods_of_class[cls] = meths
            for n in meths.values():
                for sub in ast.walk(n):
                    owner_class[sub] = cls

    # local def name -> node, per enclosing function/module body, so a
    # `jax.jit(step)` call resolves `step` defined in the same scope
    def local_defs(body_owner) -> Dict[str, ast.AST]:
        defs = {}
        for child in ast.iter_child_nodes(body_owner):
            if isinstance(child, FuncNode):
                defs[child.name] = child
        return defs

    def add(node, reason, static_nums=frozenset(),
            static_names=frozenset()):
        if node in scopes:
            return
        q = qnames.get(node, "<lambda>")
        scopes[node] = TracedScope(node, q, reason, static_nums,
                                   static_names)

    def scan_owner(owner):
        defs = local_defs(owner)
        for sub in ast.walk(owner):
            # decorated defs
            if isinstance(sub, FuncNode):
                for dec in sub.decorator_list:
                    dec_call = dec if isinstance(dec, ast.Call) else None
                    name = dotted(dec)
                    if name and tail_of(name) in JIT_TAILS:
                        add(sub, "jit-decorator")
                    elif dec_call is not None:
                        inner = unwrap_partial(dec_call)
                        target = inner if inner is not None else dec_call
                        tname = tail_of(call_head(target))
                        if tname in TRACING_HOF_TAILS:
                            nums, names = static_arg_info(target)
                            add(sub, f"{tname}-decorator", nums, names)
            if not isinstance(sub, ast.Call):
                continue
            hof = is_tracing_call(sub)
            if not hof:
                continue
            inner = unwrap_partial(sub)
            target = inner if inner is not None else sub
            nums, names = static_arg_info(target)
            for arg in target.args:
                # partial(kernel, static...) hands the wrapped def to
                # the HOF (the pallas_call / shard_map idiom): resolve
                # through it, and mark every partial-BOUND parameter
                # static — those are Python values baked at bind time
                # (causal flags, block sizes), not traced operands
                extra_static: Set[str] = set()
                if isinstance(arg, ast.Call) and \
                        tail_of(call_head(arg)) == "partial" and \
                        arg.args:
                    extra_static.update(
                        kw.arg for kw in arg.keywords if kw.arg)
                    npos = len(arg.args) - 1
                    inner = arg.args[0]
                    if npos and isinstance(inner, ast.Name) and \
                            inner.id in defs:
                        a = defs[inner.id].args
                        params = [p.arg for p in
                                  a.posonlyargs + a.args]
                        extra_static.update(params[:npos])
                    arg = inner
                snames = names | extra_static if extra_static else names
                if isinstance(arg, ast.Lambda):
                    add(arg, f"{hof}-callee", nums, snames)
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    add(defs[arg.id], f"{hof}-callee", nums, snames)
                elif isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    cls = owner_class.get(sub)
                    meth = methods_of_class.get(cls, {}) \
                        .get(arg.attr) if cls is not None else None
                    if meth is not None:
                        add(meth, f"{hof}-callee", nums, snames)

    # scan the module plus every function body (each is a def-owner)
    scan_owner(tree)
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            scan_owner(node)

    # defs nested inside a traced scope are traced too (their params
    # bind tracers when the enclosing trace calls them)
    changed = True
    while changed:
        changed = False
        for node in list(scopes):
            for sub in ast.walk(node):
                if isinstance(sub, FuncNode) and sub not in scopes:
                    add(sub, "nested-in-traced")
                    changed = True
    return list(scopes.values())


# -- PartitionSpec parsing (shared with the sharding rule family) -----------

def parse_pspec(node: ast.AST) -> Optional[Tuple]:
    """``P(...)`` / ``PartitionSpec(...)`` literal -> tuple of entries
    (each a str axis name, None, or a tuple of str for multi-axis dims).
    Returns None when the node is not a spec call or any entry is not a
    literal (a variable entry makes the spec statically unknowable —
    callers must skip, never guess)."""
    if not (isinstance(node, ast.Call)
            and tail_of(dotted(node.func)) in ("P", "PartitionSpec")
            and not node.keywords):
        return None
    entries: List = []
    for a in node.args:
        if isinstance(a, ast.Constant) and (
                a.value is None or isinstance(a.value, str)):
            entries.append(a.value)
        elif isinstance(a, (ast.Tuple, ast.List)) and a.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in a.elts):
            entries.append(tuple(e.value for e in a.elts))
        else:
            return None
    return tuple(entries)


def pspec_axes(spec: Tuple) -> Set[str]:
    """All mesh axis names a parsed spec mentions."""
    out: Set[str] = set()
    for e in spec:
        if isinstance(e, str):
            out.add(e)
        elif isinstance(e, tuple):
            out.update(e)
    return out


def format_pspec(spec: Tuple) -> str:
    return "P(" + ", ".join(
        repr(e) if not isinstance(e, tuple)
        else "(" + ", ".join(repr(x) for x in e) + ")"
        for e in spec) + ")"


# -- value-use walking ------------------------------------------------------

def value_uses(expr: ast.AST, tainted: Set[str]) -> List[ast.Name]:
    """Name nodes from `tainted` that are used AS VALUES in expr.

    Skips static contexts: x.shape/.dtype/..., len(x), isinstance(...),
    `x is None` identity tests, and keyword names."""
    hits: List[ast.Name] = []

    def walk(node):
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return                      # x.shape — static
            walk(node.value)
            return
        if isinstance(node, ast.Call):
            head = tail_of(dotted(node.func))
            if head in STATIC_CALLS:
                return                      # len(x) / isinstance(x, T)
            # method value: x.foo() uses x as value unless static attr
            walk(node.func)
            for a in node.args:
                walk(a)
            for kw in node.keywords:
                walk(kw.value)
            return
        if isinstance(node, ast.Compare):
            ops = node.ops
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in ops):
                return                      # x is None
            walk(node.left)
            for c in node.comparators:
                walk(c)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load) and node.id in tainted:
                hits.append(node)
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return hits


def assign_targets(node: ast.AST) -> List[str]:
    """Flat names assigned by an Assign/AugAssign/AnnAssign/For/With."""
    out: List[str] = []

    def collect(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            collect(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        collect(node.target)
    elif isinstance(node, ast.For):
        collect(node.target)
    elif isinstance(node, ast.withitem) and node.optional_vars:
        collect(node.optional_vars)
    return out


def propagate_taint(fn_node, seed: Set[str]) -> Set[str]:
    """Fixed-point name taint inside one function body: a name assigned
    from an expression that value-uses a tainted name becomes tainted.
    Nested defs are skipped (they get their own scope pass)."""
    tainted = set(seed)

    def stmts_of(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FuncNode + (ast.Lambda,)):
                continue
            yield child
            yield from stmts_of(child)

    def for_loop_taints(node) -> Optional[List[str]]:
        """Positional precision for `for a, b in zip(x, y)` /
        `for i, v in enumerate(x)`: taint only the targets whose
        corresponding iterable is tainted (a blanket rule would taint
        the Python-static half of a zip over (arrays, flags))."""
        it = node.iter
        if not (isinstance(it, ast.Call) and
                tail_of(dotted(it.func)) in ("zip", "enumerate")):
            return None
        srcs = list(it.args)
        if tail_of(dotted(it.func)) == "enumerate":
            srcs = [None] + srcs            # index is never tainted
        tgt = node.target
        if not isinstance(tgt, (ast.Tuple, ast.List)) or \
                len(tgt.elts) != len(srcs):
            return None
        out = []
        for elt, src in zip(tgt.elts, srcs):
            if isinstance(elt, ast.Name) and src is not None and \
                    value_uses(src, tainted):
                out.append(elt.id)
        return out

    changed = True
    while changed:
        changed = False
        for node in stmts_of(fn_node):
            value = None
            if isinstance(node, ast.Assign):
                value = node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value = node.value
            elif isinstance(node, ast.For):
                precise = for_loop_taints(node)
                if precise is not None:
                    for name in precise:
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
                    continue
                value = node.iter
            if value is None:
                continue
            if value_uses(value, tainted):
                for name in assign_targets(node):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
    return tainted


def func_of_map(tree: ast.Module) -> Dict[ast.AST, str]:
    """node -> qualname of the INNERMOST def containing it (for reports
    and line-free baseline keys). One walk per module."""
    out: Dict[ast.AST, str] = {}
    qnames = _qualname_map(tree)

    def walk(node, owner: str):
        for child in ast.iter_child_nodes(node):
            here = owner
            if isinstance(child, FuncNode):
                here = qnames.get(child, child.name)
            out[child] = here
            walk(child, here)

    walk(tree, "")
    return out
