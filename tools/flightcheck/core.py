"""flightcheck core: findings, suppressions, baseline, and the runner.

The suite is an AST-level linter for JAX/TPU-specific hazard classes —
the silent failure modes a Python test suite rarely catches because the
code *runs*, just slowly or wrongly: tracer leaks into Python control
flow, jit-cache blowups, hidden host-device synchronization on the
serving hot path, PRNG key reuse, and use-after-donation. Each rule
lives in its own module (tracer_safety, recompile, host_sync, prng,
donation) and registers a ``check(module: ast.Module, ctx: FileContext)
-> list[Finding]`` callable here.

Reporting contract:
- findings are ``file:line RULE message``; rule codes are stable.
- ``# flightcheck: disable=FC101`` (or ``disable=FC101,FC301`` /
  ``disable=all``) on the offending line or its enclosing statement
  suppresses inline — for *intended* violations (e.g. the serving
  engine's designed host-sync collection points).
- a committed baseline file grandfathers pre-existing findings: the CLI
  exits non-zero only on NEW findings. Baselines key on
  (relpath, rule, enclosing-def, normalized message) — not line
  numbers — so unrelated edits don't churn the file.
"""
from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding", "FileContext", "register", "all_rules", "check_source",
    "check_path", "load_baseline", "baseline_key", "format_finding",
    "run", "RULE_DOCS",
]

# rule code -> one-line description (filled in by checker modules)
RULE_DOCS: Dict[str, str] = {}
# rule code -> long-form rationale (surfaced by ``--explain FC###``)
RULE_EXPLAIN: Dict[str, str] = {}

_CHECKERS: List[Tuple[str, Callable]] = []


def register(name: str, fn: Callable, docs: Dict[str, str],
             explain: Optional[Dict[str, str]] = None):
    """Register a checker. ``docs`` maps each rule code the checker can
    emit to its one-line description (surfaced by ``--list-rules``);
    ``explain`` optionally maps codes to the long-form rationale behind
    ``--explain``."""
    _CHECKERS.append((name, fn))
    RULE_DOCS.update(docs)
    if explain:
        RULE_EXPLAIN.update(explain)


def all_rules() -> Dict[str, str]:
    _load_checkers()
    return dict(sorted(RULE_DOCS.items()))


@dataclass
class Finding:
    path: str            # path as given (relative preferred)
    line: int
    rule: str            # e.g. "FC101"
    message: str
    func: str = ""       # enclosing def chain, e.g. "ServingEngine.step"
    chain: str = ""      # optional call chain (host-sync findings)

    def sort_key(self):
        return (self.path, self.line, self.rule)


@dataclass
class FileContext:
    path: str
    source: str
    # line -> set of rule codes suppressed there ("all" suppresses any)
    suppressions: Dict[int, set] = field(default_factory=dict)

    def suppressed(self, line: int, rule: str) -> bool:
        for probe in (line,):
            rules = self.suppressions.get(probe)
            if rules and ("all" in rules or rule in rules):
                return True
        return False


_SUPPRESS_RE = re.compile(
    r"#\s*flightcheck:\s*disable=([A-Za-z0-9_,\s]+)")
_RULE_TOKEN_RE = re.compile(r"^(?:all|FC\d+)$")


def _parse_suppressions(source: str) -> Dict[int, set]:
    """Map line number -> suppressed rule codes. A suppression comment
    covers its own line and (expanded in check_source) the span of its
    enclosing statement. Only tokens shaped like rule codes (or `all`)
    count, so a trailing justification — `disable=FC301 designed sync`
    — still suppresses FC301."""
    out: Dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                codes = {r for r in re.split(r"[,\s]+", m.group(1))
                         if _RULE_TOKEN_RE.match(r)}
                if codes:
                    out.setdefault(tok.start[0], set()).update(codes)
    except tokenize.TokenError:
        pass
    return out


def _load_checkers():
    if _CHECKERS:
        return
    from . import (tracer_safety, recompile, host_sync, prng, donation,
                   sharding, memory)
    for mod in (tracer_safety, recompile, host_sync, prng, donation,
                sharding, memory):
        mod.setup(register)


def check_source(source: str, path: str = "<string>",
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run every registered checker over one source blob."""
    _load_checkers()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "FC000",
                        f"syntax error: {e.msg}")]
    suppressions = _parse_suppressions(source)
    # a suppression anywhere inside a multi-line statement covers the
    # whole statement's span — a comment on the first line must keep
    # suppressing when a reformat moves the sink call to a continuation
    if suppressions:
        spans = [(n.lineno, getattr(n, "end_lineno", n.lineno) or
                  n.lineno)
                 for n in ast.walk(tree) if isinstance(n, ast.stmt)]
        for line, sup_rules in list(suppressions.items()):
            best = None
            for lo, hi in spans:
                if lo <= line <= hi and (
                        best is None or (hi - lo) < (best[1] - best[0])):
                    best = (lo, hi)
            if best:
                for ln in range(best[0], best[1] + 1):
                    suppressions.setdefault(ln, set()).update(sup_rules)
    ctx = FileContext(path=path, source=source,
                      suppressions=suppressions)
    findings: List[Finding] = []
    for _name, fn in _CHECKERS:
        for f in fn(tree, ctx):
            if rules and f.rule not in rules:
                continue
            # a suppression on the finding line OR on the first line of
            # its enclosing simple statement wins
            if ctx.suppressed(f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=Finding.sort_key)
    return findings


def _iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


# finding paths anchor at the repository root (the directory holding
# the `tools` package) regardless of the lint root or the cwd — so
# `paddle_tpu/` and `paddle_tpu/inference/` runs produce IDENTICAL
# paths, baseline keys stay stable across invocation shapes, and the
# jaxpr cross-check's path matching works from any entry point
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _repo_rel(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(_REPO_ROOT + os.sep):
        return os.path.relpath(ap, _REPO_ROOT)
    return path


def check_path(root: str,
               rules: Optional[Sequence[str]] = None,
               cache: Optional["FindingsCache"] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(root):
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        rel = _repo_rel(path)
        if cache is not None:
            hit = cache.lookup(src, rules, path=rel)
            if hit is not None:
                findings.extend(hit)
                continue
        file_findings = check_source(src, rel, rules)
        if cache is not None:
            cache.store(src, rules, file_findings, path=rel)
        findings.extend(file_findings)
    if cache is not None:
        cache.save()
    return findings


# -- baseline --------------------------------------------------------------

def baseline_key(f: Finding) -> str:
    """Line-number-free identity so unrelated edits don't churn the
    baseline: path, rule, enclosing def, message."""
    return f"{f.path}::{f.rule}::{f.func}::{f.message}"


def load_baseline(path: str) -> set:
    if not path or not os.path.exists(path):
        return set()
    out = set()
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def write_baseline(path: str, findings: Sequence[Finding]):
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# flightcheck baseline — grandfathered findings.\n"
                 "# One key per line: path::RULE::func::message.\n"
                 "# Remove entries as the findings are fixed; never add\n"
                 "# new ones without a written justification.\n")
        for key in sorted({baseline_key(f) for f in findings}):
            fh.write(key + "\n")


def format_finding(f: Finding) -> str:
    loc = f"{f.path}:{f.line}"
    msg = f"{loc}: {f.rule} [{f.func or '<module>'}] {f.message}"
    if f.chain:
        msg += f"\n    call chain: {f.chain}"
    return msg


def run(root: str, baseline_path: Optional[str] = None,
        rules: Optional[Sequence[str]] = None,
        cache_path: Optional[str] = "default"
        ) -> Tuple[List[Finding], List[Finding]]:
    """Returns (new_findings, baselined_findings).

    ``cache_path="default"`` uses the on-disk findings cache (keyed by
    file content hash + checker-source hash, so it can never serve
    stale verdicts); ``None`` disables it."""
    cache = None
    if cache_path is not None:
        from .cache import FindingsCache, DEFAULT_CACHE
        cache = FindingsCache(
            DEFAULT_CACHE if cache_path == "default" else cache_path)
    findings = check_path(root, rules, cache=cache)
    baseline = load_baseline(baseline_path) if baseline_path else set()
    new, old = [], []
    for f in findings:
        (old if baseline_key(f) in baseline else new).append(f)
    return new, old
