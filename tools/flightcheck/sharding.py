"""SPMD/sharding rule family (FC601-FC606): the shard_map/GSPMD layer.

This repo has been burned at exactly this layer twice (PR 3): jax 0.4.x
cannot lower collectives — or in-body GSPMD constraints — inside a
*partially*-manual shard_map (a fatal SPMD-partitioner CHECK, not a
catchable error), and a shard_map that *claims* replicated outputs with
the rep checker disabled silently returns per-shard garbage. Before the
serving engine is sharded over a ``tp`` axis (ROADMAP item 1), these
hazards need static eyes:

- FC601 collective over an axis name the enclosing shard_map never
  binds (unbound at trace time, or an auto axis under partial-manual —
  the spmd_partitioner.cc:512 abort);
- FC602 out_specs claim replication while check_vma/check_rep is OFF
  and the body establishes replication nowhere (no psum/pmean/pmax/
  pmin/all_gather/pvary) — each shard returns its own value and the
  claim silently picks shard 0;
- FC603 ``with_sharding_constraint`` inside a FULLY-manual shard_map —
  there are no auto axes to constrain; on jax 0.4.x hybrid meshes this
  is the hard-abort PR 3 fixed twice. The sanctioned pattern gates the
  hint on ``partial_manual_ok()`` (pp_schedule) and is exempt;
- FC604 a dimension sharded over mesh axes whose static size is not
  divisible by the (statically known) mesh axis size — XLA pads
  silently and collectives carry the padding;
- FC605 PartitionSpec drift: the same parameter name annotated with
  conflicting literal specs across call sites, or disagreeing with the
  canonical ``SpecLayout`` table
  (paddle_tpu/distributed/spec_layout.py, parsed syntactically);
- FC606 a donated jit argument whose in_sharding differs from every
  out_sharding — XLA cannot alias mismatched layouts, the donation
  silently fails and the "in-place" update double-buffers.

All rules resolve meshes/specs/callees statically and SKIP whenever a
value is not a literal — low-false-positive by construction, like the
rest of the suite.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, FileContext
from .scopes import (FuncNode, dotted, format_pspec, func_of_map,
                     parse_pspec, pspec_axes, tail_of, unwrap_partial)

# collective tails -> index of the positional axis-name argument
COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "pshuffle": 1, "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "pbroadcast": 1, "pvary": 1, "axis_index": 0,
}
AXIS_KWARGS = ("axis_name", "axes")

# calls whose presence in a shard_map body can establish replication
# over a manual axis (FC602's escape hatch)
REPLICATING_TAILS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                     "pvary"}

ARRAY_CTOR_TAILS = {"zeros", "ones", "empty", "full"}


def _literal_axis_names(call: ast.Call) -> Optional[List[str]]:
    """Axis-name string literals of a collective call, or None when the
    axis argument is not a literal (variable axis names are common and
    fine — we only judge what we can prove)."""
    tail = tail_of(dotted(call.func))
    pos = COLLECTIVE_AXIS_ARG.get(tail)
    node = None
    if pos is not None and len(call.args) > pos:
        node = call.args[pos]
    for kw in call.keywords:
        if kw.arg in AXIS_KWARGS:
            node = kw.value
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return [e.value for e in node.elts]
    return None


# -- mesh resolution --------------------------------------------------------

def _literal_str_tuple(node) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return None


def _literal_int_tuple(node) -> Optional[Tuple[int, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    return None


def _mesh_from_call(call: ast.Call) -> Optional[Dict[str, Optional[int]]]:
    """``Mesh(devs, ("a","b"))`` / ``create_mesh((2,4), ("a","b"))`` ->
    {axis: size-or-None}. Sizes resolve when the device grid is a
    literal-shaped construction (create_mesh shape tuple, or
    ``np.arange(n).reshape(a, b)``)."""
    tail = tail_of(dotted(call.func))
    names: Optional[Tuple[str, ...]] = None
    sizes: Optional[Tuple[int, ...]] = None
    if tail == "Mesh":
        args = list(call.args)
        kw = {k.arg: k.value for k in call.keywords}
        names_node = args[1] if len(args) > 1 else kw.get("axis_names")
        if names_node is None:
            return None
        names = _literal_str_tuple(names_node)
        dev = args[0] if args else None
        # np.arange(n).reshape(a, b) — the common literal grid (the
        # chain's base is a Call, so match the .reshape attr directly)
        if isinstance(dev, ast.Call) and \
                isinstance(dev.func, ast.Attribute) and \
                dev.func.attr == "reshape":
            if len(dev.args) == 1:
                sizes = _literal_int_tuple(dev.args[0])
                if sizes is None and \
                        isinstance(dev.args[0], ast.Constant) and \
                        isinstance(dev.args[0].value, int):
                    sizes = (dev.args[0].value,)
            elif dev.args:
                sizes = _literal_int_tuple(
                    ast.Tuple(elts=list(dev.args), ctx=ast.Load()))
    elif tail == "create_mesh":
        args = list(call.args)
        kw = {k.arg: k.value for k in call.keywords}
        shape_node = args[0] if args else kw.get("shape")
        names_node = args[1] if len(args) > 1 else kw.get("dim_names")
        if names_node is None:
            return None
        names = _literal_str_tuple(names_node)
        sizes = _literal_int_tuple(shape_node) if shape_node is not None \
            else None
    if not names:
        return None
    if sizes is not None and len(sizes) != len(names):
        sizes = None
    return {n: (sizes[i] if sizes is not None else None)
            for i, n in enumerate(names)}


def _mesh_table(tree: ast.Module) -> Dict[str, Dict[str, Optional[int]]]:
    """Assigned name (full dotted AND attr tail) -> mesh axes. A name
    bound to two DIFFERENT meshes is dropped (ambiguous)."""
    out: Dict[str, Dict[str, Optional[int]]] = {}
    dead: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        axes = _mesh_from_call(node.value)
        if axes is None:
            continue
        keys: Set[str] = set()
        for t in node.targets:
            name = dotted(t)
            if name:
                keys.add(name)
                keys.add(tail_of(name))
        for k in keys:
            if k in out and out[k] != axes:
                dead.add(k)
            out[k] = axes
    for k in dead:
        out.pop(k, None)
    return out


def _resolve_mesh(expr, mesh_table) -> Optional[Dict[str, Optional[int]]]:
    name = dotted(expr)
    if not name:
        return None
    return mesh_table.get(name) or mesh_table.get(tail_of(name))


# -- shard_map call-site discovery ------------------------------------------

@dataclass
class SMSite:
    call: ast.Call
    lineno: int
    callee: Optional[ast.AST] = None           # def/lambda node
    mesh_axes: Optional[Dict[str, Optional[int]]] = None
    manual_axes: Optional[Set[str]] = None     # None = fully manual
    ambiguous: bool = False                    # **kwargs at the site
    check_off: bool = False                    # check_vma/check_rep False
    out_specs: List[Tuple] = field(default_factory=list)
    out_specs_known: bool = False

    def bound_axes(self) -> Optional[Set[str]]:
        """Axis names the body may use collectives over, or None when
        statically unknowable."""
        if self.ambiguous:
            return None
        if self.manual_axes is not None:
            return set(self.manual_axes)
        if self.mesh_axes is not None:
            return set(self.mesh_axes)
        return None


def _def_tables(tree: ast.Module):
    """(name -> unique def node or None-if-ambiguous,
    class methods map, node -> owner class)."""
    by_name: Dict[str, Optional[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            if node.name in by_name and by_name[node.name] is not node:
                by_name[node.name] = None
            else:
                by_name.setdefault(node.name, node)
    methods: Dict[ast.AST, Dict[str, ast.AST]] = {}
    owner: Dict[ast.AST, ast.AST] = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            meths = {n.name: n for n in cls.body
                     if isinstance(n, FuncNode)}
            methods[cls] = meths
            for n in meths.values():
                for sub in ast.walk(n):
                    owner[sub] = cls
    return by_name, methods, owner


def _resolve_callee(node: ast.AST, site_call: ast.Call, by_name, methods,
                    owner) -> Optional[ast.AST]:
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, ast.Call) and \
            tail_of(dotted(node.func)) == "partial" and node.args:
        return _resolve_callee(node.args[0], site_call, by_name, methods,
                               owner)
    name = dotted(node)
    if not name:
        return None
    if name.startswith("self."):
        cls = owner.get(site_call)
        if cls is not None:
            return methods.get(cls, {}).get(name.split(".", 1)[1])
        return None
    return by_name.get(name)


def _parse_out_specs(node) -> Tuple[List[Tuple], bool]:
    """out_specs AST -> (list of parsed specs, fully-known?)."""
    if node is None:
        return [], False
    single = parse_pspec(node)
    if single is not None:
        return [single], True
    if isinstance(node, (ast.Tuple, ast.List)):
        specs, known = [], True
        for e in node.elts:
            s = parse_pspec(e)
            if s is None:
                known = False
            else:
                specs.append(s)
        return specs, known
    return [], False


def _find_sites(tree: ast.Module, mesh_table) -> List[SMSite]:
    by_name, methods, owner = _def_tables(tree)
    sites: List[SMSite] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = node
        if tail_of(dotted(node.func)) == "partial":
            inner = unwrap_partial(node)
            if inner is None:
                continue
            target = inner
        if tail_of(dotted(target.func)) != "shard_map":
            continue
        site = SMSite(call=node, lineno=node.lineno)
        kw = {k.arg: k.value for k in target.keywords}
        site.ambiguous = any(k.arg is None for k in target.keywords)
        if target.args:
            site.callee = _resolve_callee(target.args[0], node, by_name,
                                          methods, owner)
        mesh_node = kw.get("mesh") or (
            target.args[1] if len(target.args) > 1 else None)
        if mesh_node is not None:
            site.mesh_axes = _resolve_mesh(mesh_node, mesh_table)
        an = kw.get("axis_names")
        if an is not None:
            names = _literal_str_tuple(an)
            if names is None and isinstance(an, ast.Set) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in an.elts):
                names = tuple(e.value for e in an.elts)
            if names is not None:
                site.manual_axes = set(names)
            else:
                site.ambiguous = True
        for flag in ("check_vma", "check_rep"):
            v = kw.get(flag)
            if isinstance(v, ast.Constant) and v.value is False:
                site.check_off = True
        site.out_specs, site.out_specs_known = _parse_out_specs(
            kw.get("out_specs"))
        sites.append(site)
    return sites


def _body_nodes(callee: ast.AST, skip: Set[int]):
    """Walk a callee body, skipping nested shard_map callees (their
    collectives bind against THEIR site, not this one)."""
    stack = [callee]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if id(child) in skip:
                continue
            yield child
            stack.append(child)


def _calls_partial_manual_ok(fn_node: ast.AST) -> bool:
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Call) and \
                tail_of(dotted(sub.func)) == "partial_manual_ok":
            return True
    return False


# -- FC604/FC605 support ----------------------------------------------------

def _shape_of_ctor(node) -> Optional[Tuple[int, ...]]:
    """jnp.zeros((2, 3)) / np.ones((4,)) / jnp.full((2, 2), v) -> shape."""
    if not (isinstance(node, ast.Call)
            and tail_of(dotted(node.func)) in ARRAY_CTOR_TAILS
            and node.args):
        return None
    shp = _literal_int_tuple(node.args[0])
    if shp is None and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, int):
        shp = (node.args[0].value,)
    return shp


def _local_shapes(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """name (dotted) -> literal array shape, dropped on conflict."""
    out: Dict[str, Tuple[int, ...]] = {}
    dead: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        shp = _shape_of_ctor(node.value)
        if shp is None:
            continue
        for t in node.targets:
            name = dotted(t)
            if not name:
                continue
            if name in out and out[name] != shp:
                dead.add(name)
            out[name] = shp
    for k in dead:
        out.pop(k, None)
    return out


_CANONICAL_CACHE: Dict[str, Dict[str, Tuple]] = {}


def canonical_specs(repo_root: str) -> Dict[str, Tuple]:
    """Parse CANONICAL_SPECS out of the committed SpecLayout table —
    syntactically, so linting never imports the linted package."""
    path = os.path.join(repo_root, "paddle_tpu", "distributed",
                        "spec_layout.py")
    if path in _CANONICAL_CACHE:
        return _CANONICAL_CACHE[path]
    table: Dict[str, Tuple] = {}
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        _CANONICAL_CACHE[path] = table
        return table
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not any(dotted(t) == "CANONICAL_SPECS" for t in targets):
            continue
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                spec = parse_pspec(v)
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and spec is not None:
                    table[k.value] = spec
    _CANONICAL_CACHE[path] = table
    return table


def _spec_conflicts(a: Tuple, b: Tuple) -> bool:
    """Suffix comparison: stacked layouts prepend bookkeeping dims, so
    ('pp', None, 'tp') agrees with canonical (None, 'tp') but
    ('tp', None) does not."""
    n = min(len(a), len(b))
    if n == 0:
        return False
    return a[-n:] != b[-n:]


# -- the checker ------------------------------------------------------------

def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    _annotate_parents(tree)     # FC604 climbs NamedSharding→device_put
    findings: List[Finding] = []
    owner_of = func_of_map(tree)
    mesh_table = _mesh_table(tree)
    sites = _find_sites(tree, mesh_table)
    callee_ids = {id(s.callee) for s in sites if s.callee is not None}

    def qual(node) -> str:
        return owner_of.get(node, "")

    # FC601 / FC602 / FC603 — per shard_map site
    for site in sites:
        if site.callee is None:
            continue
        skip = callee_ids - {id(site.callee)}
        body = list(_body_nodes(site.callee, skip))

        bound = site.bound_axes()
        if bound is not None:
            for node in body:
                if not isinstance(node, ast.Call):
                    continue
                tail = tail_of(dotted(node.func))
                if tail not in COLLECTIVE_AXIS_ARG:
                    continue
                axes = _literal_axis_names(node)
                if axes is None:
                    continue
                for ax in axes:
                    if ax not in bound:
                        mode = ("manual axes {%s}" % ", ".join(
                            sorted(site.manual_axes))
                            if site.manual_axes is not None
                            else "mesh axes {%s}" % ", ".join(
                                sorted(bound)))
                        findings.append(Finding(
                            ctx.path, node.lineno, "FC601",
                            f"collective '{tail}' over axis '{ax}' "
                            f"which the enclosing shard_map (line "
                            f"{site.lineno}) does not bind ({mode}); "
                            f"unbound at trace time — or an auto axis, "
                            f"which the SPMD partitioner hard-aborts "
                            f"on", qual(node)))

        if site.check_off and site.out_specs_known and any(
                len(s) == 0 for s in site.out_specs):
            has_escape = any(
                isinstance(n, ast.Call)
                and tail_of(dotted(n.func)) in REPLICATING_TAILS
                for n in body)
            if not has_escape:
                findings.append(Finding(
                    ctx.path, site.lineno, "FC602",
                    "out_specs claims a fully-replicated output (P()) "
                    "with check_vma/check_rep disabled, but the body "
                    "never establishes replication (no psum/pmean/pmax/"
                    "pmin/all_gather/pvary) — each shard returns its "
                    "own value and the claim silently takes one "
                    "shard's", qual(site.call)))

        fully_manual = (site.manual_axes is None and not site.ambiguous)
        if fully_manual:
            for node in body:
                if isinstance(node, ast.Call) and tail_of(dotted(
                        node.func)) == "with_sharding_constraint":
                    if _calls_partial_manual_ok(site.callee):
                        continue
                    findings.append(Finding(
                        ctx.path, node.lineno, "FC603",
                        f"with_sharding_constraint inside a FULLY-"
                        f"manual shard_map (line {site.lineno}): no "
                        f"auto axes exist to constrain, and jax 0.4.x "
                        f"hard-aborts lowering it on hybrid meshes "
                        f"(spmd_partitioner.cc:512) — gate the hint on "
                        f"partial_manual_ok() or drop it",
                        qual(node)))

    # FC604 — divisibility at device_put/NamedSharding sites
    shapes = _local_shapes(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and
                tail_of(dotted(node.func)) == "NamedSharding"
                and len(node.args) >= 2):
            continue
        mesh_axes = _resolve_mesh(node.args[0], mesh_table)
        spec = parse_pspec(node.args[1])
        if mesh_axes is None or spec is None:
            continue
        # the array being placed: device_put(x, NamedSharding(...))
        parent = getattr(node, "_fc_parent", None)
        shp = None
        if parent is not None and isinstance(parent, ast.Call):
            x = parent.args[0] if parent.args else None
            shp = _shape_of_ctor(x) if x is not None else None
            if shp is None and x is not None:
                name = dotted(x)
                shp = shapes.get(name) if name else None
        if shp is None or len(spec) > len(shp):
            continue
        for dim, entry in enumerate(spec):
            axes = [entry] if isinstance(entry, str) else (
                list(entry) if isinstance(entry, tuple) else [])
            total = 1
            known = bool(axes)
            for ax in axes:
                size = mesh_axes.get(ax)
                if size is None:
                    known = False
                    break
                total *= size
            if known and shp[dim] % total:
                findings.append(Finding(
                    ctx.path, node.lineno, "FC604",
                    f"dim {dim} (size {shp[dim]}) sharded over mesh "
                    f"axes {axes} of total size {total} — not "
                    f"divisible; XLA pads silently and every "
                    f"collective on this value moves the padding",
                    qual(node)))

    # FC605 — spec drift across call sites + canonical table
    from .core import _REPO_ROOT
    canon = canonical_specs(_REPO_ROOT)
    seen: Dict[str, Tuple[Tuple, int]] = {}
    for node in ast.walk(tree):
        bindings: List[Tuple[str, Tuple, int]] = []
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                spec = parse_pspec(v)
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and spec is not None:
                    bindings.append((k.value, spec, v.lineno))
        elif isinstance(node, ast.Call) and tail_of(dotted(
                node.func)) in ("with_sharding_constraint",
                                "device_put") and len(node.args) >= 2:
            tgt = dotted(node.args[0])
            sh = node.args[1]
            spec = parse_pspec(sh)
            if spec is None and isinstance(sh, ast.Call) and \
                    tail_of(dotted(sh.func)) == "NamedSharding" and \
                    len(sh.args) >= 2:
                spec = parse_pspec(sh.args[1])
            if tgt and spec is not None:
                bindings.append((tail_of(tgt), spec, node.lineno))
        for name, spec, lineno in bindings:
            prev = seen.get(name)
            # suffix comparison: a stacked-trunk spec ('pp', None, 'tp')
            # agrees with its unstacked (None, 'tp') form
            if prev is not None and _spec_conflicts(spec, prev[0]):
                findings.append(Finding(
                    ctx.path, lineno, "FC605",
                    f"'{name}' annotated {format_pspec(spec)} here but "
                    f"{format_pspec(prev[0])} at line {prev[1]} — "
                    f"conflicting specs for the same value compose "
                    f"into silent all-gathers; pick one (the "
                    f"SpecLayout table) and reuse it",
                    qual(node)))
            else:
                seen[name] = (spec, lineno)
            cspec = canon.get(name)
            if cspec is not None and (
                    pspec_axes(spec) & pspec_axes(cspec)) and \
                    _spec_conflicts(spec, cspec):
                findings.append(Finding(
                    ctx.path, lineno, "FC605",
                    f"'{name}' annotated {format_pspec(spec)} but the "
                    f"canonical SpecLayout table "
                    f"(paddle_tpu/distributed/spec_layout.py) says "
                    f"{format_pspec(cspec)} — drift from the "
                    f"canonical layout", qual(node)))

    # FC606 — donation/sharding mismatch on jit sites
    findings.extend(_check_donation_specs(tree, ctx, owner_of))

    findings = [f for f in findings if not ctx.suppressed(f.line, f.rule)]
    return findings


def _check_donation_specs(tree, ctx, owner_of) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = node
        if tail_of(dotted(node.func)) == "partial":
            inner = unwrap_partial(node)
            if inner is None:
                continue
            target = inner
        if tail_of(dotted(target.func)) not in ("jit", "pjit"):
            continue
        kw = {k.arg: k.value for k in target.keywords}
        donate = kw.get("donate_argnums")
        ins, outs = kw.get("in_shardings"), kw.get("out_shardings")
        if donate is None or ins is None or outs is None:
            continue
        try:
            donated = ast.literal_eval(donate)
        except (ValueError, TypeError, SyntaxError):
            continue
        if isinstance(donated, int):
            donated = (donated,)
        in_specs, in_known = _parse_out_specs(ins)
        out_specs, out_known = _parse_out_specs(outs)
        if not (in_known and out_known and out_specs):
            continue
        for pos in donated:
            if not isinstance(pos, int) or pos >= len(in_specs):
                continue
            spec = in_specs[pos]
            if all(spec != o for o in out_specs):
                out.append(Finding(
                    ctx.path, target.lineno, "FC606",
                    f"donated arg {pos} has in_sharding "
                    f"{format_pspec(spec)} but no output shares it "
                    f"(outs: "
                    f"{', '.join(format_pspec(o) for o in out_specs)})"
                    f" — XLA cannot alias mismatched shardings, the "
                    f"donation silently fails and the buffer "
                    f"double-allocates", owner_of.get(node, "")))
    return out


def _annotate_parents(tree: ast.Module):
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._fc_parent = node  # type: ignore[attr-defined]


EXPLAIN = {
    "FC601": (
        "A collective (psum/ppermute/all_gather/...) names a mesh axis "
        "the enclosing shard_map never binds. Under a fully-manual "
        "shard_map the bound axes are the mesh's; under partial-manual "
        "(axis_names={...}) they are exactly that subset — a collective "
        "over an auto axis is the jax 0.4.x SPMD-partitioner hard "
        "abort (spmd_partitioner.cc:512) PR 3 worked around. Fix: bind "
        "the axis (add it to axis_names / the mesh) or reduce over the "
        "right name."),
    "FC602": (
        "shard_map's out_specs is a CLAIM. P() claims every shard "
        "holds the same value; the rep/vma checker normally verifies "
        "it, but this site disables the checker (check_vma=False) and "
        "the body never runs a replication-establishing op (psum, "
        "pmean, pmax, pmin, all_gather, pvary). One shard's value is "
        "silently broadcast as 'the' answer. Fix: psum (or all_gather) "
        "the output, or declare the honest per-shard spec."),
    "FC603": (
        "with_sharding_constraint steers GSPMD *auto* axes. Inside a "
        "FULLY-manual shard_map there are none — the hint is dead at "
        "best, and on jax 0.4.x hybrid meshes lowering it is a fatal "
        "XLA CHECK (the exact trap PR 3 fixed twice). Fix: gate the "
        "hint on partial_manual_ok() (see pp_schedule/llama_pp) or "
        "drop it in manual regions."),
    "FC604": (
        "A dimension sharded over a mesh axis must divide by the axis "
        "size; otherwise GSPMD pads the shards and every collective "
        "moves (and every reduction sums) the padding — correct-ish "
        "numerics at best, silent garbage at the edges at worst. Fix: "
        "pad explicitly to a multiple, or reshape the sharded dim."),
    "FC605": (
        "The same parameter annotated with two different "
        "PartitionSpecs (across call sites, or against the canonical "
        "SpecLayout table in paddle_tpu/distributed/spec_layout.py) "
        "makes XLA insert resharding all-gathers at the boundary — "
        "the #1 silent perf leak when hand-threading specs. Fix: "
        "import the spec from the one canonical table."),
    "FC606": (
        "donate_argnums promises an input buffer to an output, but "
        "aliasing requires matching shardings. A donated input whose "
        "in_sharding matches no out_sharding cannot be aliased: jax "
        "warns once, the 'in-place' KV-pool-style update silently "
        "double-buffers, and HBM headroom halves. Fix: make the "
        "donated input's spec equal its output's (the multi-GiB "
        "buffers this matters for are updated in place, not "
        "resharded)."),
}


def setup(register):
    register("sharding", check, {
        "FC601": "collective over an axis the enclosing shard_map does "
                 "not bind",
        "FC602": "replicated out_specs claim with rep-check disabled "
                 "and no psum/pvary in the body",
        "FC603": "with_sharding_constraint inside a fully-manual "
                 "shard_map (jax 0.4.x lowering trap)",
        "FC604": "sharded dimension not divisible by the mesh axis "
                 "size",
        "FC605": "conflicting PartitionSpecs for the same value across "
                 "call sites / vs the SpecLayout table",
        "FC606": "donated buffer whose sharding matches no output (the "
                 "donation silently fails)",
    }, EXPLAIN)
