"""flightcheck — framework-aware static analysis for JAX/TPU hazards.

A lint suite for the bug classes that make JAX code on TPUs fail
*silently*: tracer leaks into Python control flow (FC101-FC103), jit
recompilation storms (FC201-FC202), hidden host-device syncs on the
serving hot path (FC301), PRNG key reuse and dead derivations
(FC401-FC402), use-after-donation (FC501), and SPMD/sharding hazards at
the shard_map/GSPMD layer (FC601-FC606: unbound collective axes, fake
replication claims, in-body GSPMD constraints in fully-manual regions,
mesh divisibility, PartitionSpec drift vs the canonical SpecLayout
table, donation/sharding mismatch). Two dynamic cross-checks keep the
static pass honest: ``--jaxpr`` traces the paged-decode/serving entry
points and refutes/confirms AST verdicts, and the comm audit
(``tools.flightcheck.comm_audit``) abstract-traces the distributed
entry points on the 8-device mesh and pins every program's collectives
(kind/axis/payload bytes/count per dispatch) against a committed
expectations file.

Usage::

    python -m tools.flightcheck paddle_tpu/            # lint the tree
    python -m tools.flightcheck --list-rules
    python -m tools.flightcheck --explain FC603        # rule rationale
    python -m tools.flightcheck --changed paddle_tpu/  # git-diff scoped
    python -m tools.flightcheck --jaxpr paddle_tpu/    # + jaxpr mode
    python -m tools.flightcheck.comm_audit             # comm audit gate

Findings cache: results are memoized on disk keyed by file content hash
and a checker-source hash (``tools/flightcheck/.findings_cache.json``),
so repeat runs over an unchanged tree skip re-parsing; ``--no-cache``
bypasses it.

Suppress a single intended finding inline::

    toks = np.asarray(ch["toks"])  # flightcheck: disable=FC301

Grandfather pre-existing findings in ``tools/flightcheck/baseline.txt``
(see ``--write-baseline``); the CLI fails only on NEW findings.
"""
from .core import (Finding, all_rules, baseline_key, check_path,
                   check_source, format_finding, load_baseline, run)

__all__ = ["Finding", "all_rules", "baseline_key", "check_path",
           "check_source", "format_finding", "load_baseline", "run",
           "DEFAULT_BASELINE"]

import os as _os

DEFAULT_BASELINE = _os.path.join(_os.path.dirname(_os.path.abspath(
    __file__)), "baseline.txt")
