"""flightcheck — framework-aware static analysis for JAX/TPU hazards.

A lint suite for the bug classes that make JAX code on TPUs fail
*silently*: tracer leaks into Python control flow (FC101-FC103), jit
recompilation storms (FC201-FC202), hidden host-device syncs on the
serving hot path (FC301), PRNG key reuse and dead derivations
(FC401-FC402), and use-after-donation (FC501). An optional jaxpr-backed
mode (``--jaxpr``) traces the paged-decode/serving entry points and
cross-checks the AST verdicts, keeping the static pass low-false-
positive.

Usage::

    python -m tools.flightcheck paddle_tpu/            # lint the tree
    python -m tools.flightcheck --list-rules
    python -m tools.flightcheck --jaxpr paddle_tpu/    # + jaxpr mode

Suppress a single intended finding inline::

    toks = np.asarray(ch["toks"])  # flightcheck: disable=FC301

Grandfather pre-existing findings in ``tools/flightcheck/baseline.txt``
(see ``--write-baseline``); the CLI fails only on NEW findings.
"""
from .core import (Finding, all_rules, baseline_key, check_path,
                   check_source, format_finding, load_baseline, run)

__all__ = ["Finding", "all_rules", "baseline_key", "check_path",
           "check_source", "format_finding", "load_baseline", "run",
           "DEFAULT_BASELINE"]

import os as _os

DEFAULT_BASELINE = _os.path.join(_os.path.dirname(_os.path.abspath(
    __file__)), "baseline.txt")
