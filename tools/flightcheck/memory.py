"""Memory-hazard rules (FC7xx): pool-scale residency and footprint.

The serving engine's HBM budget is dominated by a handful of pool
planes — the paged KV cache (``cache_k``/``cache_v`` and their int8
scale planes) and the S-LoRA adapter pool — whose residency claims
(int8 pages at a fraction of f32 bytes, in-place donation on every
dispatch, flat carry bytes across multi-step scans) are exactly the
kind of thing that regresses silently: the program still computes the
right numbers, it just holds two copies of a multi-GiB buffer while
doing so. These rules flag the four statically-visible ways that
happens:

- FC701 — a *flat whole-table gather* (``jnp.take(pool, tables)`` /
  ``pool[tables]`` / the ``_dequantize_gather`` helper fed a full
  block table) materializes a ``[rows, max_pages, ...]`` copy of the
  pool, and outer-product broadcasts of pool-scale operands do the
  same through shape expansion. Also enumerates pool gathers that rely
  on the default out-of-bounds mode (NaN fill for floats).
- FC702 — dtype-footprint leaks: an f32 constant or whole-plane
  ``astype`` forcing a bf16/int8 plane to upcast, a dtype-less
  ``jnp.zeros`` scattered into a pool plane, or a quantized
  ``(values, scales)`` unpack whose scales half is silently dropped.
- FC703 — donation *effectiveness* (FC501 covers use-after-donate):
  a jit whose target returns a pool-plane parameter that is not in
  ``donate_argnums``, or a donated plane returned with a changed
  dtype/shape so XLA cannot alias the buffers.
- FC704 — ``lax.scan`` carries that grow per iteration (self-concat
  in the step body) or carry pool planes bound to non-donated jit
  arguments (the multi_step=k hot spot: every step then
  double-buffers the plane).

Pool vocabulary is seeded from the committed SpecLayout table
(``canonical_specs``) — the same source of truth the FC6xx sharding
rules lint against — plus the conventional local aliases
(``k_pool``/``v_pool``/``plane``/...). Bare ``k``/``v`` are
deliberately excluded from the gather/dtype rules (they are ubiquitous
attention operands); they count only where position corroborates them
(jit parameters and scan-carry elements).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FileContext, _REPO_ROOT
from .donation import _donate_nums, _jit_target
from .scopes import FuncNode, dotted, func_of_map, tail_of
from .sharding import canonical_specs

# -- pool-plane vocabulary --------------------------------------------------

_POOL_FALLBACK = frozenset({
    "cache_k", "cache_v", "cache_k_scale", "cache_v_scale", "lora_pool"})

_POOL_ALIASES = frozenset({
    "pool", "plane", "k_cache", "v_cache", "kv_cache", "k_pool",
    "v_pool", "kv_pool", "lora_pool"})

_POOL_SUFFIXES = ("_pool", "_plane")

# weak names: accepted only where position corroborates them (jit
# params / scan carries), never for the gather/dtype rules
_POOL_WEAK = frozenset({"k", "v", "kp", "vp", "kv"})

_FLOAT_DTYPES = {"float32", "float64", "f32", "f64"}


def _canonical_pool_names() -> frozenset:
    table = canonical_specs(_REPO_ROOT)
    names = {n for n in table
             if n.startswith("cache_") or n.endswith("_pool")}
    return frozenset(names) if names else _POOL_FALLBACK


def _pool_name(name: Optional[str], canon: frozenset) -> bool:
    if not name:
        return False
    return (name in canon or name in _POOL_ALIASES
            or name.endswith(_POOL_SUFFIXES))


def _pool_operand(node: ast.AST, pool: Set[str],
                  canon: frozenset) -> Optional[str]:
    """Dotted name of the pool plane an expression denotes, seeing
    through per-layer subscripts (``k_pool[li]``), or None."""
    if isinstance(node, ast.Subscript):
        return _pool_operand(node.value, pool, canon)
    name = dotted(node)
    if name is None:
        return None
    t = tail_of(name)
    if t in pool or _pool_name(t, canon):
        return name
    return None


def _own_nodes(owner):
    """Every AST node in ``owner``'s body, excluding nested def/lambda
    subtrees (those get their own scope pass)."""
    stack = list(ast.iter_child_nodes(owner))
    while stack:
        n = stack.pop()
        if isinstance(n, FuncNode + (ast.Lambda,)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _params_of(fn) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _pool_locals(fn, canon: frozenset) -> Set[str]:
    """Names in this scope that denote a pool plane: pool-named params,
    direct aliases, per-layer subscripts of a pool, and tuple-unpack
    halves of a quantized plane. Deliberately NOT full value taint —
    a matmul result derived from the pool is an activation, not a
    plane."""
    pool: Set[str] = set()
    if isinstance(fn, (ast.Lambda,) + FuncNode):
        for p in _params_of(fn):
            if _pool_name(p, canon):
                pool.add(p)
    if isinstance(fn, ast.Lambda):
        return pool
    changed = True
    while changed:
        changed = False
        for st in _own_nodes(fn):
            if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                continue
            val = st.value
            if isinstance(val, ast.Subscript):
                src = tail_of(dotted(val.value))
            else:
                src = tail_of(dotted(val))
            if src is None or not (src in pool or _pool_name(src, canon)):
                continue
            tgt = st.targets[0]
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for e in elts:
                if isinstance(e, ast.Name) and e.id not in pool:
                    pool.add(e.id)
                    changed = True
    return pool


# -- FC701: flat whole-table gathers / pool-scale broadcasts ----------------

_FLAT_HELPERS = {"_dequantize_gather", "dequantize_gather"}


def _strip_flatten(node: ast.AST) -> ast.AST:
    """idx.reshape(-1) / .ravel() / .flatten() -> idx"""
    while isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in ("reshape", "ravel", "flatten"):
        node = node.func.value
    return node


def _table_like(node: ast.AST) -> Optional[str]:
    """A WHOLE block-table operand (not a per-step column of one)."""
    node = _strip_flatten(node)
    name = dotted(node)          # Subscript (tables[:, i]) -> None
    if name is None:
        return None
    t = (tail_of(name) or "").lower()
    if "table" in t or "pages" in t:
        return name
    return None


def _has_none_expand(sub: ast.Subscript) -> bool:
    """P[:, None] style rank expansion."""
    sl = sub.slice
    elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
    return any(isinstance(e, ast.Constant) and e.value is None
               for e in elts)


def _check_fc701(fn, pool, canon, owner_of, ctx, out):
    for n in _own_nodes(fn):
        if isinstance(n, ast.Call):
            head = tail_of(dotted(n.func))
            if head == "take" and n.args:
                # jnp.take(P, idx, ...) or P.take(idx, ...)
                if isinstance(n.func, ast.Attribute) and \
                        _pool_operand(n.func.value, pool, canon):
                    plane = _pool_operand(n.func.value, pool, canon)
                    idx = n.args[0] if n.args else None
                else:
                    plane = _pool_operand(n.args[0], pool, canon)
                    idx = n.args[1] if len(n.args) > 1 else None
                if plane is None:
                    continue
                tbl = _table_like(idx) if idx is not None else None
                if tbl is not None:
                    out.append(Finding(
                        ctx.path, n.lineno, "FC701",
                        f"flat gather of pool plane '{plane}' over the "
                        f"whole block table '{tbl}' materializes a "
                        f"[rows, max_pages, ...] copy of the pool — "
                        f"walk pages online (fori_loop) or gather one "
                        f"column per step",
                        owner_of.get(n, "")))
                elif not any(kw.arg == "mode" for kw in n.keywords):
                    out.append(Finding(
                        ctx.path, n.lineno, "FC701",
                        f"jnp.take on pool plane '{plane}' relies on "
                        f"the default out-of-bounds mode (NaN fill for "
                        f"floats) — pass mode= explicitly "
                        f"(mode='clip' matches the page allocator's "
                        f"sentinel convention)",
                        owner_of.get(n, "")))
            elif head in _FLAT_HELPERS and len(n.args) >= 2:
                tbl = _table_like(n.args[1])
                if tbl is not None:
                    out.append(Finding(
                        ctx.path, n.lineno, "FC701",
                        f"'{head}' fed the whole block table '{tbl}' "
                        f"materializes every page of the pool plane at "
                        f"once — restrict to the rows' own pages or "
                        f"walk pages online",
                        owner_of.get(n, "")))
        elif isinstance(n, ast.Subscript) and \
                isinstance(n.ctx, ast.Load):
            plane = _pool_operand(n.value, pool, canon)
            if plane is not None and plane != dotted(n.value):
                continue    # per-layer subscript of a pool, fine
            if plane is not None:
                tbl = _table_like(n.slice)
                if tbl is not None:
                    out.append(Finding(
                        ctx.path, n.lineno, "FC701",
                        f"fancy-index '{plane}[{tbl}]' is a flat "
                        f"whole-table gather — materializes "
                        f"[rows, max_pages, ...] of the pool",
                        owner_of.get(n, "")))
        elif isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
            sides = [n.left, n.right]
            expanded = [s for s in sides
                        if isinstance(s, ast.Subscript)
                        and _has_none_expand(s)]
            if len(expanded) == 2:
                for s in expanded:
                    plane = _pool_operand(s.value, pool, canon)
                    if plane is not None:
                        out.append(Finding(
                            ctx.path, n.lineno, "FC701",
                            f"outer-product broadcast of pool-scale "
                            f"operand '{plane}' materializes a "
                            f"rank-expanded intermediate of the whole "
                            f"pool — contract inside a kernel or per "
                            f"page instead",
                            owner_of.get(n, "")))
                        break


# -- FC702: dtype-footprint leaks -------------------------------------------

def _is_f32_dtype(node: ast.AST) -> bool:
    name = tail_of(dotted(node))
    if name in _FLOAT_DTYPES:
        return True
    return isinstance(node, ast.Constant) and node.value in _FLOAT_DTYPES


def _float_const(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp):
        return _float_const(node.operand)
    return False


def _check_fc702(fn, pool, canon, owner_of, ctx, out):
    # dtype-less fills (jnp.zeros(shape) with no dtype=) by local name
    fills: Set[str] = set()
    loads: Dict[str, int] = {}
    for n in _own_nodes(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            loads[n.id] = loads.get(n.id, 0) + 1
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                isinstance(n.value, ast.Call):
            h = tail_of(dotted(n.value.func))
            if h in ("zeros", "ones", "full") and \
                    not any(kw.arg == "dtype" for kw in n.value.keywords):
                nargs = 2 if h == "full" else 1
                if len(n.value.args) <= nargs:
                    fills.add(n.targets[0].id)

    for n in _own_nodes(fn):
        # f32 constant arithmetic on a bare plane
        if isinstance(n, ast.BinOp):
            for a, b in ((n.left, n.right), (n.right, n.left)):
                plane = _pool_operand(a, pool, canon)
                if plane is not None and _float_const(b):
                    out.append(Finding(
                        ctx.path, n.lineno, "FC702",
                        f"f32 constant arithmetic on pool plane "
                        f"'{plane}' upcasts the whole plane inside the "
                        f"traced body — fold the constant into the "
                        f"dequant scale or cast it to the plane dtype",
                        owner_of.get(n, "")))
                    break
        # whole-plane astype to f32
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "astype" and n.args:
            plane = _pool_operand(n.func.value, pool, canon)
            if plane is not None and _is_f32_dtype(n.args[0]):
                out.append(Finding(
                    ctx.path, n.lineno, "FC702",
                    f"whole-plane astype of pool plane '{plane}' to "
                    f"float32 multiplies resident bytes by 2-4x — "
                    f"dequantize per-page inside the attention kernel "
                    f"instead",
                    owner_of.get(n, "")))
        # jnp.where/minimum/maximum/clip mixing a plane with f32 consts
        elif isinstance(n, ast.Call) and \
                tail_of(dotted(n.func)) in ("where", "minimum",
                                            "maximum", "clip"):
            planes = [_pool_operand(a, pool, canon) for a in n.args]
            if any(planes) and any(_float_const(a) for a in n.args):
                plane = next(p for p in planes if p)
                out.append(Finding(
                    ctx.path, n.lineno, "FC702",
                    f"'{tail_of(dotted(n.func))}' mixes pool plane "
                    f"'{plane}' with a float constant — promotion "
                    f"upcasts the whole plane; cast the constant to "
                    f"the plane dtype",
                    owner_of.get(n, "")))
        # dtype-less fill scattered into a plane: P.at[...].set(z)
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("set", "add") and n.args and \
                isinstance(n.args[0], ast.Name) and \
                n.args[0].id in fills:
            recv = n.func.value          # P.at[idx]
            if isinstance(recv, ast.Subscript) and \
                    isinstance(recv.value, ast.Attribute) and \
                    recv.value.attr == "at":
                plane = _pool_operand(recv.value.value, pool, canon)
                if plane is not None:
                    out.append(Finding(
                        ctx.path, n.lineno, "FC702",
                        f"dtype-less fill '{n.args[0].id}' (defaults "
                        f"to float32) scattered into pool plane "
                        f"'{plane}' upcasts the plane — pass the "
                        f"plane's dtype to the zeros/ones call",
                        owner_of.get(n, "")))

        # quantized (values, scales) unpack dropping the scales half
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Tuple) and \
                len(n.targets[0].elts) == 2 and \
                all(isinstance(e, ast.Name) for e in n.targets[0].elts):
            plane = _pool_operand(n.value, pool, canon)
            if plane is None:
                continue
            vals, scales = (e.id for e in n.targets[0].elts)
            if loads.get(vals, 0) > 0 and loads.get(scales, 0) == 0:
                out.append(Finding(
                    ctx.path, n.lineno, "FC702",
                    f"quantized plane '{plane}' unpacked to "
                    f"({vals}, {scales}) but the scales half "
                    f"'{scales}' is never used — downstream math "
                    f"silently consumes raw int8 codes",
                    owner_of.get(n, "")))


# -- FC703/FC704 shared: jit-target registry --------------------------------

def _resolve_fn(arg: ast.AST, defs: Dict[str, ast.AST]):
    """Resolve a jit/scan function operand to its def or lambda node,
    seeing through wrapper calls (``tp_wrap(f, ...)``, ``partial(f,
    ...)``) by their first positional argument, and through
    ``self.method`` by name."""
    hops = 0
    while isinstance(arg, ast.Call) and arg.args and hops < 3:
        arg = arg.args[0]
        hops += 1
    if isinstance(arg, ast.Lambda):
        return arg
    name = dotted(arg)
    return defs.get(tail_of(name) or "") if name else None


def _defs_by_name(tree: ast.Module) -> Dict[str, ast.AST]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, FuncNode)}


def _jit_registry(tree: ast.Module, defs: Dict[str, ast.AST]):
    """target def/lambda node -> {"donate": union of donated positions,
    "sites": [(lineno, donate_set)]} over every resolvable jit site."""
    reg: Dict[ast.AST, Dict] = {}

    def note(node, donate: Set[int], lineno: int):
        ent = reg.setdefault(node, {"donate": set(), "sites": []})
        ent["donate"] |= donate
        ent["sites"].append((lineno, set(donate)))

    for n in ast.walk(tree):
        if isinstance(n, FuncNode):
            for dec in n.decorator_list:
                if isinstance(dec, ast.Call):
                    jit = _jit_target(dec)
                    if jit is not None:
                        note(n, _donate_nums(jit), dec.lineno)
                elif tail_of(dotted(dec)) in ("jit", "pjit"):
                    note(n, set(), dec.lineno)
        if not isinstance(n, ast.Call):
            continue
        jit = _jit_target(n)
        if jit is None or not jit.args:
            continue
        target = _resolve_fn(jit.args[0], defs)
        if target is not None:
            note(target, _donate_nums(jit), n.lineno)
    return reg


def _donatable_params(fn) -> List[Tuple[int, str]]:
    """(donate-position, name) pairs, counting from the first non-self
    parameter the way a bound-method jit does."""
    params = _params_of(fn)
    off = 1 if params and params[0] in ("self", "cls") else 0
    return [(i - off, p) for i, p in enumerate(params)
            if p not in ("self", "cls")]


def _returned_names(fn) -> Set[str]:
    if isinstance(fn, ast.Lambda):
        exprs = [fn.body]
    else:
        exprs = [r.value for r in _own_nodes(fn)
                 if isinstance(r, ast.Return) and r.value is not None]
    # only names that ARE the returned value (recursing through
    # tuple/list structure) count — a name consumed inside a call or
    # arithmetic in the return expression is not the plane coming back
    names: Set[str] = set()

    def collect(e):
        if isinstance(e, ast.Name) and isinstance(e.ctx, ast.Load):
            names.add(e.id)
        elif isinstance(e, (ast.Tuple, ast.List)):
            for el in e.elts:
                collect(el)

    for e in exprs:
        collect(e)
    return names


def _pool_param(name: str, canon: frozenset) -> bool:
    return _pool_name(name, canon) or name in _POOL_WEAK


def _check_fc703(tree, reg, canon, owner_of, ctx, out):
    for target, ent in reg.items():
        pairs = _donatable_params(target)
        returned = _returned_names(target)
        qual = owner_of.get(target, getattr(target, "name", "<lambda>"))
        tname = getattr(target, "name", "<lambda>")
        # (a) a site with no donation, while the target returns a
        # pool-plane parameter: the in-place update double-buffers
        pool_returned = [(i, p) for i, p in pairs
                         if _pool_param(p, canon) and p in returned]
        if pool_returned:
            for lineno, donate in ent["sites"]:
                missing = [(i, p) for i, p in pool_returned
                           if i not in donate]
                if missing:
                    pos = ", ".join(str(i) for i, _ in missing)
                    names = ", ".join(f"'{p}'" for _, p in missing)
                    out.append(Finding(
                        ctx.path, lineno, "FC703",
                        f"jit of '{tname}' returns pool plane "
                        f"parameter(s) {names} without donating them "
                        f"— the in-place update double-buffers the "
                        f"pool (add donate_argnums position(s) {pos})",
                        qual))
        # (b) donated plane returned with changed dtype/shape: the
        # donation cannot alias
        donated_names = {p for i, p in pairs if i in ent["donate"]}
        if not donated_names:
            continue
        for n in _own_nodes(target) if not isinstance(
                target, ast.Lambda) else ():
            rebind = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                rebind = n
            if rebind is None:
                continue
            val = rebind.value
            if not (isinstance(val, ast.Call) and
                    isinstance(val.func, ast.Attribute) and
                    val.func.attr in ("astype", "reshape")):
                continue
            base = dotted(val.func.value)
            tgts = rebind.targets[0]
            tgt_names = [e.id for e in (
                tgts.elts if isinstance(tgts, (ast.Tuple, ast.List))
                else [tgts]) if isinstance(e, ast.Name)]
            if base in donated_names and base in tgt_names and \
                    base in returned:
                what = ("dtype" if val.func.attr == "astype"
                        else "shape")
                out.append(Finding(
                    ctx.path, n.lineno, "FC703",
                    f"donated plane '{base}' is returned with a "
                    f"changed {what} ('{val.func.attr}') — XLA cannot "
                    f"alias the buffers, so the donation silently "
                    f"double-buffers; convert outside the jit boundary "
                    f"or donate a buffer of the output {what}",
                    qual))


# -- FC704: scan-carry residency --------------------------------------------

_GROW_CALLS = {"concatenate", "concat", "append", "hstack", "vstack",
               "column_stack", "pad"}


def _check_fc704(tree, reg, defs, canon, owner_of, ctx, out):
    for fn in [n for n in ast.walk(tree) if isinstance(n, FuncNode)]:
        for n in _own_nodes(fn):
            if not (isinstance(n, ast.Call) and
                    tail_of(dotted(n.func)) == "scan" and
                    len(n.args) >= 2):
                continue
            local = {c.name: c for c in ast.iter_child_nodes(fn)
                     if isinstance(c, FuncNode)}
            step = _resolve_fn(n.args[0], {**defs, **local})
            qual = owner_of.get(n, fn.name)
            # (a) growing carry: step rebinds a returned name by
            # concatenating it with itself
            if step is not None and not isinstance(step, ast.Lambda):
                ret = _returned_names(step)
                for st in _own_nodes(step):
                    if not (isinstance(st, ast.Assign) and
                            len(st.targets) == 1 and
                            isinstance(st.targets[0], ast.Name) and
                            isinstance(st.value, ast.Call)):
                        continue
                    name = st.targets[0].id
                    if tail_of(dotted(st.value.func)) not in _GROW_CALLS:
                        continue
                    self_ref = any(
                        isinstance(s, ast.Name) and s.id == name and
                        isinstance(s.ctx, ast.Load)
                        for s in ast.walk(st.value))
                    if self_ref and name in ret:
                        out.append(Finding(
                            ctx.path, st.lineno, "FC704",
                            f"scan carry '{name}' grows every "
                            f"iteration ('{tail_of(dotted(st.value.func))}' "
                            f"with itself) — carries must be "
                            f"fixed-shape; preallocate and write with "
                            f".at[i].set, or emit via the ys output",
                            owner_of.get(st, qual)))
            # (b) pool planes carried through a non-donated jit arg
            ent = reg.get(fn)
            if ent is None:
                continue
            donated = {p for i, p in _donatable_params(fn)
                       if i in ent["donate"]}
            param_names = {p for _, p in _donatable_params(fn)}
            init = n.args[1]
            elts = init.elts if isinstance(init, (ast.Tuple, ast.List)) \
                else [init]
            for e in elts:
                name = tail_of(dotted(e))
                if not name or not _pool_param(name, canon):
                    continue
                if name in param_names and name not in donated:
                    out.append(Finding(
                        ctx.path, n.lineno, "FC704",
                        f"scan carries pool plane '{name}', a "
                        f"NON-donated argument of jitted '{fn.name}' — "
                        f"every step double-buffers the plane; add its "
                        f"position to donate_argnums",
                        qual))


# -- the checker ------------------------------------------------------------

def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    canon = _canonical_pool_names()
    owner_of = func_of_map(tree)
    defs = _defs_by_name(tree)
    reg = _jit_registry(tree, defs)
    findings: List[Finding] = []

    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, FuncNode)]
    for fn in scopes:
        pool = _pool_locals(fn, canon) if isinstance(fn, FuncNode) \
            else set()
        # module level: only explicitly pool-named globals count
        _check_fc701(fn, pool, canon, owner_of, ctx, findings)
        _check_fc702(fn, pool, canon, owner_of, ctx, findings)

    _check_fc703(tree, reg, canon, owner_of, ctx, findings)
    _check_fc704(tree, reg, defs, canon, owner_of, ctx, findings)

    # dedup (a node can be visited from nested scope iterations)
    seen = set()
    out = []
    for f in sorted(findings, key=lambda f: f.sort_key()):
        key = (f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


EXPLAIN = {
    "FC701": (
        "A paged pool is only cheap while it is addressed one page at "
        "a time. `jnp.take(pool, block_tables)` (or "
        "`pool[block_tables]`, or feeding `_dequantize_gather` a whole "
        "table) gathers EVERY row's EVERY page into a dense "
        "[rows, max_pages, block, heads, d] intermediate — the exact "
        "bug that once made ragged serving slower than dense: HBM "
        "traffic scales with the pool, not the tokens. Outer-product "
        "broadcasts of pool-scale operands (`a[:, None] * b[None, :]`) "
        "materialize the same way through shape expansion. Fix: walk "
        "pages online (fori_loop over a per-step table column, "
        "online-softmax style) or gather only the rows' own pages. "
        "The rule also enumerates pool gathers that rely on jnp.take's "
        "default out-of-bounds mode — unused page slots hold sentinel "
        "ids, and the default fills float gathers with NaN; pass "
        "mode= explicitly."),
    "FC702": (
        "Quantized and bf16 planes earn their bytes only if nothing "
        "silently promotes them. An f32 literal in plane arithmetic, "
        "a whole-plane `.astype(jnp.float32)`, or a dtype-less "
        "`jnp.zeros(...)` scattered into a plane each force XLA to "
        "materialize an f32 copy of the pool (2-4x bytes) inside the "
        "traced body. The quantized-tuple variant is worse than a "
        "footprint leak: unpacking `(values, scales)` and dropping "
        "the scales half feeds raw int8 codes to downstream math — "
        "numerically wrong, not just big. Fix: fold constants into "
        "the dequant scale, dequantize per-page inside the kernel, "
        "pass the plane dtype to fills, and thread both tuple halves."),
    "FC703": (
        "donate_argnums is a promise, not a guarantee. Two ways it "
        "silently fails to save memory: (a) the jit never donates a "
        "pool plane its target updates and returns — functional "
        "in-place updates (`pool.at[...].set`) then allocate a second "
        "full plane per dispatch; (b) the plane IS donated but comes "
        "back with a different dtype or shape, which XLA cannot alias "
        "(input and output buffers must match byte-for-byte), so the "
        "donation is accepted and ignored. FC501 catches reading a "
        "donated buffer after the call; FC703 catches donations that "
        "never took effect at all. Fix: donate every returned plane, "
        "and keep dtype/shape fixed across the jit boundary."),
    "FC704": (
        "A lax.scan carry is resident for the whole scan. Two hazard "
        "shapes: (a) a carry that grows per iteration "
        "(concatenating itself) — scan requires fixed carry shapes, "
        "and the workaround people reach for (padding, re-tracing) "
        "multiplies bytes by the trip count; preallocate and write "
        "with .at[i].set, or emit per-step values through the ys "
        "output. (b) the multi_step=k hot spot: the carry holds whole "
        "pool planes, which is exactly right for fused decode — but "
        "only if the enclosing jit donates them. A non-donated plane "
        "carried through k steps double-buffers the pool for the "
        "duration of every dispatch."),
}


def setup(register):
    register("memory", check, {
        "FC701": "flat whole-table gather / broadcast materializes a "
                 "pool-scale intermediate (or pool take without "
                 "explicit OOB mode)",
        "FC702": "dtype-footprint leak: f32 op upcasts a quantized "
                 "plane, or a (values, scales) path drops the scales",
        "FC703": "pool-plane jit argument whose donation is missing "
                 "or cannot alias (dtype/shape change)",
        "FC704": "lax.scan carry grows per iteration or carries a "
                 "non-donated pool plane",
    }, EXPLAIN)
