"""Per-program HBM audit: abstract-trace every registered entry point
and pin its memory shape — argument/output/peak-temp bytes, donated
bytes actually aliased, and scan-carry residency — against a committed
expectations file.

The FC7xx rules (tools/flightcheck/memory.py) catch the memory hazards
visible in SOURCE; this audit pins the ones visible only in the traced
PROGRAM: the engine's headline memory claims — int8 KV pages at a
fraction of f32 bytes (ISSUE 13), donation keeping the multi-GiB pool
single-buffered across every dispatch, the multi_step=k fused window
carrying pool planes at FLAT cost in k (ISSUE 16), data-parallel rows
adding zero per-replica bytes (ISSUE 11) — all regress silently: the
program still computes the right numbers, it just holds more HBM while
doing so, and no numeric test notices until an OOM on real hardware.

Accounting is jaxpr-level — deterministic, backend-free, and the same
on the CPU gate as anywhere else (XLA's ``memory_analysis()`` is
backend-specific and unavailable or host-shaped on the CPU gate, so it
is surfaced informationally via ``--xla`` but never pinned):

- ``arg_bytes`` / ``out_bytes``: summed over the traced avals;
- ``peak_temp_bytes``: a liveness scan over the program's equations
  (allocate at the defining equation, free after the last use), with
  control-flow bodies (scan/while/cond/pjit) contributing their own
  recursive peak while they execute — an upper-bound shape, not an XLA
  buffer assignment, which is exactly what makes it stable enough to
  commit;
- ``donated_bytes``: invars marked donated on the pjit equation;
- ``aliased_bytes``: the donated bytes XLA can actually alias — a
  donated invar only aliases an output of identical shape AND dtype,
  so a plane returned upcast/reshaped silently drops out of this
  number (the FC703 failure mode, measured);
- ``scan_carry_bytes``: the widest scan carry in the program (the
  multi_step hot spot: the carry holds whole pool planes).

Every numeric field is pinned exactly except ``peak_temp_bytes``
(a relative tolerance band absorbs jax-version jitter in equation
order). On top of per-program pins, cross-program RELATIONS encode the
paper-level claims directly:

- ``serving.ragged_kv8_tp2`` pool (donated) bytes strictly below
  ``serving.ragged_tp2_fp32`` at equal geometry (int8 + f32 sidecar
  scales vs f32 planes: > 1.5x smaller);
- ``serving.ragged_k4_tp2`` scan-carry bytes FLAT in k — bounded by
  its own donated pool planes plus slack, never k x;
- ``serving.ragged_dp2_tp2`` byte-identical to the single-engine tp
  program: dp adds zero per-replica step bytes.

``python -m tools.flightcheck.mem_audit`` fails on ANY drift;
regenerate deliberately with ``--write`` after a reviewed change.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from .comm_audit import ensure_devices, program_names, programs

EXPECTATIONS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "mem_expectations.json")

# fields pinned exactly vs within a relative band
_EXACT_FIELDS = ("arg_bytes", "out_bytes", "donated_bytes",
                 "aliased_bytes", "scan_carry_bytes")
_BAND_FIELDS = {"peak_temp_bytes": 0.10}


# -- jaxpr byte accounting --------------------------------------------------

def _aval_bytes(aval) -> int:
    import numpy as np
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    try:
        item = np.dtype(aval.dtype).itemsize
    except TypeError:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * item


def _vars_bytes(vs) -> int:
    total = 0
    for v in vs:
        if hasattr(v, "val"):        # literal
            continue
        total += _aval_bytes(getattr(v, "aval", None))
    return total


def _sub_jaxprs(eqn):
    """Inner jaxprs of a control-flow/pjit equation."""
    out = []
    for key in ("jaxpr", "body_jaxpr", "cond_jaxpr", "call_jaxpr"):
        v = eqn.params.get(key)
        if v is None:
            continue
        core = getattr(v, "jaxpr", v)
        if hasattr(core, "eqns"):
            out.append(core)
    for br in eqn.params.get("branches", ()) or ():
        core = getattr(br, "jaxpr", br)
        if hasattr(core, "eqns"):
            out.append(core)
    return out


def _peak_temp(jaxpr, flags: set, depth: int = 0) -> int:
    """Liveness-scan peak of intermediate bytes: each equation's
    outputs allocate when it runs and free after their last use;
    control-flow bodies contribute their own recursive peak while
    their equation executes. Inputs and outputs of ``jaxpr`` itself
    are excluded (they are argument/output bytes, counted separately).
    """
    if depth > 6:                    # pathological nesting guard
        flags.add("depth-capped")
        return 0
    eqns = jaxpr.eqns
    if not eqns:
        return 0
    out_set = {id(v) for v in jaxpr.outvars}
    last_use: Dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name == "while":
            flags.add("while-approx")
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[id(v)] = i
    live = 0
    peak = 0
    freed_at: Dict[int, List] = {}
    for i, eqn in enumerate(eqns):
        inner = 0
        for sub in _sub_jaxprs(eqn):
            inner = max(inner, _peak_temp(sub, flags, depth + 1))
        alloc = sum(_aval_bytes(v.aval) for v in eqn.outvars
                    if id(v) not in out_set)
        peak = max(peak, live + alloc + inner)
        live += alloc
        # free temps whose last use was THIS equation
        for v in eqn.invars:
            if hasattr(v, "val") or id(v) in out_set:
                continue
            if last_use.get(id(v)) == i and id(v) not in freed_at:
                freed_at[id(v)] = True
                live -= _aval_bytes(v.aval)
        live = max(live, 0)
    return peak


def _walk_eqns(jaxpr, depth: int = 0):
    if depth > 6:
        return
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub, depth + 1)


def _donation(jaxpr) -> Tuple[int, int]:
    """(donated_bytes, aliased_bytes) summed over pjit equations.
    Aliased = donated invars greedily matched to same-(shape, dtype)
    outputs — the match XLA's donation aliasing actually requires, so
    a donated plane returned with a changed dtype/shape counts as
    donated but NOT aliased (FC703's failure mode, measured)."""
    donated = 0
    aliased = 0
    for eqn in _walk_eqns(jaxpr):
        marks = eqn.params.get("donated_invars")
        if not marks or not any(marks):
            continue
        outs = {}
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            key = (tuple(aval.shape), str(aval.dtype))
            outs[key] = outs.get(key, 0) + 1
        for v, is_don in zip(eqn.invars, marks):
            if not is_don or hasattr(v, "val"):
                continue
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            nb = _aval_bytes(aval)
            donated += nb
            key = (tuple(aval.shape), str(aval.dtype))
            if outs.get(key, 0) > 0:
                outs[key] -= 1
                aliased += nb
    return donated, aliased


def _scan_carry(jaxpr) -> int:
    """Widest scan carry (bytes) anywhere in the program."""
    widest = 0
    for eqn in _walk_eqns(jaxpr):
        if eqn.primitive.name != "scan":
            continue
        n = int(eqn.params.get("num_carry", 0))
        widest = max(widest, sum(_aval_bytes(v.aval)
                                 for v in eqn.outvars[:n]))
    return widest


def audit_jaxpr(closed_jaxpr) -> dict:
    jx = closed_jaxpr.jaxpr
    flags: set = set()
    entry = {
        "method": "jaxpr",
        "arg_bytes": _vars_bytes(jx.invars),
        "out_bytes": _vars_bytes(jx.outvars),
        "peak_temp_bytes": _peak_temp(jx, flags),
        "scan_carry_bytes": _scan_carry(jx),
    }
    donated, aliased = _donation(jx)
    entry["donated_bytes"] = donated
    entry["aliased_bytes"] = aliased
    entry["flags"] = sorted(flags)
    return entry


# -- audit / expectations ---------------------------------------------------

def audit(only: Optional[str] = None) -> Dict[str, dict]:
    """Trace and byte-account every registered program (or the
    ``only`` name-prefix subset). A program that cannot trace IS a
    bug: it becomes an {"error": ...} entry and fails the compare."""
    ensure_devices()
    import jax
    report: Dict[str, dict] = {}
    for name, build in sorted(programs().items()):
        if only and not name.startswith(only):
            continue
        try:
            fn, args = build()
            jx = jax.make_jaxpr(fn)(*args)
            report[name] = audit_jaxpr(jx)
        except Exception as e:
            report[name] = {"error": f"{type(e).__name__}: {e}"}
    return report


def xla_memory(only: Optional[str] = None) -> Dict[str, dict]:
    """Informational XLA-side numbers (``memory_analysis()``) where the
    installed backend provides them — never pinned: the committed
    expectations must be identical on the CPU gate and a TPU host."""
    ensure_devices()
    import jax
    out: Dict[str, dict] = {}
    for name, build in sorted(programs().items()):
        if only and not name.startswith(only):
            continue
        try:
            fn, args = build()
            compiled = jax.jit(fn).lower(*args).compile()
            ma = compiled.memory_analysis()
            out[name] = {
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0)),
                "temp_bytes": int(
                    getattr(ma, "temp_size_in_bytes", 0)),
            }
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def relations(report: Dict[str, dict]) -> List[str]:
    """Cross-program memory relations (empty list = all hold). Each
    encodes a paper-level claim; checked only when both endpoints are
    in ``report`` (scoped --only runs skip them)."""
    problems: List[str] = []

    def get(name):
        e = report.get(name)
        return e if e is not None and "error" not in e else None

    fp32 = get("serving.ragged_tp2_fp32")
    kv8 = get("serving.ragged_kv8_tp2")
    k4 = get("serving.ragged_k4_tp2")
    dp2 = get("serving.ragged_dp2_tp2")

    if fp32 and kv8:
        # quantized pool planes (int8 values + f32 sidecar scales) must
        # be well under the f32 planes at the same geometry
        f, q = fp32["donated_bytes"], kv8["donated_bytes"]
        if not q or q * 1.5 >= f:
            problems.append(
                f"relation kv8<fp32: quantized pool donated bytes {q} "
                f"not < fp32 {f} by >1.5x — the int8 layout stopped "
                f"paying for itself")
    if k4 and fp32:
        # the fused multi-step carry holds the pool planes ONCE — flat
        # in k: its bytes track the single-step program's carry (plus
        # per-step token/position slack), NOT k x anything
        carry, base = k4["scan_carry_bytes"], fp32["scan_carry_bytes"]
        if carry <= 0:
            problems.append(
                "relation k4-carry: multi-step program has no scan "
                "carry — the fused window lost its scan")
        elif carry > base * 1.25 + 4096:
            problems.append(
                f"relation k4-carry-flat: carry bytes {carry} exceed "
                f"the single-step program's carry {base} + slack — "
                f"the carry is no longer flat in k")
    if dp2 and fp32:
        diff = [f for f in _EXACT_FIELDS + tuple(_BAND_FIELDS)
                if dp2.get(f) != fp32.get(f)]
        if diff:
            problems.append(
                f"relation dp2==fp32: replica program differs from the "
                f"single-engine tp program on {', '.join(diff)} — data "
                f"parallelism must add zero per-replica step bytes")
    return problems


def save(report: Dict[str, dict], path: str = EXPECTATIONS):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load(path: str = EXPECTATIONS) -> Dict[str, dict]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def compare(actual: Dict[str, dict],
            expected: Dict[str, dict]) -> List[str]:
    """Human-readable drift list (empty = match): exact on every field
    except the tolerance-banded ones. Only programs present in
    ``actual`` are compared (supports scoped runs), but a program
    expected and no longer REGISTERED is drift."""
    problems: List[str] = []
    names = set(programs())
    for name in sorted(set(expected) - names):
        problems.append(f"{name}: expected but no longer registered")
    for name, got in sorted(actual.items()):
        want = expected.get(name)
        if want is None:
            problems.append(f"{name}: not in expectations file "
                            f"(regenerate with --write)")
            continue
        if "error" in got:
            problems.append(f"{name}: TRACE FAILURE {got['error']}")
            continue
        for f in _EXACT_FIELDS:
            if got.get(f) != want.get(f):
                problems.append(
                    f"{name}: {f} drifted — expected {want.get(f)}, "
                    f"got {got.get(f)}")
        for f, band in _BAND_FIELDS.items():
            w, g = want.get(f, 0), got.get(f, 0)
            if abs(g - w) > band * max(abs(w), 1):
                problems.append(
                    f"{name}: {f} outside the ±{int(band * 100)}% "
                    f"band — expected {w}, got {g}")
        if got.get("flags") != want.get("flags"):
            problems.append(
                f"{name}: flags drifted — expected {want.get('flags')}"
                f", got {got.get('flags')}")
    return problems


def format_report(report: Dict[str, dict]) -> str:
    lines = []
    for name, entry in sorted(report.items()):
        if "error" in entry:
            lines.append(f"{name}: TRACE FAILURE {entry['error']}")
            continue
        flag = (" [" + ",".join(entry["flags"]) + "]"
                if entry.get("flags") else "")
        lines.append(f"{name}:{flag}")
        lines.append(
            f"    args {entry['arg_bytes']:>12} B   "
            f"out {entry['out_bytes']:>12} B   "
            f"peak-temp {entry['peak_temp_bytes']:>12} B")
        lines.append(
            f"    donated {entry['donated_bytes']:>9} B   "
            f"aliased {entry['aliased_bytes']:>8} B   "
            f"scan-carry {entry['scan_carry_bytes']:>11} B")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m tools.flightcheck.mem_audit",
        description="jaxpr-level HBM audit of the serving/distributed "
                    "entry points")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the committed expectations file")
    ap.add_argument("--only", default=None,
                    help="audit only programs with this name prefix")
    ap.add_argument("--xla", action="store_true",
                    help="also print XLA memory_analysis numbers "
                         "(informational; never pinned)")
    args = ap.parse_args(argv)

    report = audit(only=args.only)
    if args.only and not report:
        print(f"mem audit: --only {args.only!r} matches no registered "
              f"program; known: {', '.join(program_names())}",
              file=sys.stderr)
        return 2
    print(format_report(report))
    if args.xla:
        print("\nXLA memory_analysis (informational):")
        for name, e in sorted(xla_memory(only=args.only).items()):
            print(f"  {name}: {json.dumps(e)}")
    errors = [n for n, e in report.items() if "error" in e]
    rel_problems = relations(report)
    if args.write:
        if errors:
            print(f"mem audit: NOT writing expectations — "
                  f"{len(errors)} trace failure(s)")
            return 1
        if rel_problems:
            print("mem audit: NOT writing expectations — relation "
                  "violation(s):")
            for p in rel_problems:
                print("  " + p)
            return 1
        if args.only:
            merged = load() if os.path.exists(EXPECTATIONS) else {}
            merged.update(report)
            report = merged
        save(report)
        print(f"mem audit: expectations written -> {EXPECTATIONS}")
        return 0
    if not os.path.exists(EXPECTATIONS):
        print("mem audit: no expectations file committed — run with "
              "--write")
        return 1
    problems = compare(report, load()) + rel_problems
    if problems:
        print("\nmem audit: DRIFT detected")
        for p in problems:
            print("  " + p)
        return 1
    print(f"\nmem audit: {len(report)} program(s) match the committed "
          f"expectations; relations hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
