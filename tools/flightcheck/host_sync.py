"""Host-sync rule (FC301): blocking host↔device transfers on the
serving hot path.

Hazard: on TPU the scheduler's throughput lives or dies by keeping the
device queue full. A single stray ``np.asarray(device_value)`` /
``jax.device_get`` / implicit ``bool(device_value)`` inside the
dispatch path blocks the host on the device (and through a remote
tunnel costs a full round trip, ~75 ms measured in this repo), turning
the async pipeline back into lock-step. The engine's design makes
collection (``ServingEngine._collect_oldest`` /
``_collect_prefill_run``) the ONLY blocking points — those carry
explicit inline suppressions with a justification; anything else that
trips this rule is a scheduling bug. Real example: before PR 2, prefill
results were fetched inside admission, which silently absorbed in-flight
decode time into the prefill wall clock — exactly the call shape this
rule reports.

Mechanics: for every serving-scheduler-shaped class (a ``step`` method
plus ``_dispatch*``/``_collect*`` methods), build the self-method call
graph reachable from the hot entry points, then taint device values at
two levels — ARR (2): results of ``jnp.*``/``jax.*``/jitted ``*_j`` /
``*_impl`` calls and subscripts into device containers; CONT (1):
containers (deques/dicts/lists) those values were stored into. Host
materialization sinks fire on ARR (and on CONT for the whole-container
transfers ``np.asarray``/``jax.device_get``); ``int()``/``float()`` /
``np.asarray``/``jax.device_get`` results are HOST (laundering), so the
designed sync point doesn't taint everything downstream of it. Each
finding reports the call chain from the entry point.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, FileContext
from .scopes import FuncNode, dotted, tail_of

_ENTRY_NAMES = ("step",)
_ENTRY_PREFIXES = ("_dispatch", "_collect", "_admit")

# call heads producing device values (level 2)
_DEVICE_HEAD_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.",
                         "jax.nn.")
_DEVICE_EXACT = {"jax.device_put"}
# attribute-call suffixes that are jitted/compiled callables by this
# repo's convention (serving engine jits everything into *_j; decoder
# impls are *_impl)
_DEVICE_CALL_SUFFIXES = ("_j", "_impl")

# laundering: these RETURN host values (and are sinks when fed device)
_LAUNDER_HEADS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array", "jax.device_get", "int", "float",
                  "bool"}
_LAUNDER_METHODS = {"item", "tolist", "numpy"}
# container ops whose result keeps the container's element level
_CONTAINER_GETTERS = {"popleft", "pop", "get", "peek", "copy"}

_SINK_WHOLE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "jax.device_get", "jax.block_until_ready"}
_SINK_CASTS = {"bool", "int", "float"}
_SINK_METHODS = {"block_until_ready", "item", "tolist"}


class _Taint:
    """Expression device-level evaluator for one method body."""

    def __init__(self, local: Dict[str, int], attrs: Dict[str, int]):
        self.local = local      # local name -> level
        self.attrs = attrs      # self-attr name -> level

    def level(self, expr) -> int:
        if expr is None:
            return 0
        if isinstance(expr, ast.Name):
            return self.local.get(expr.id, 0)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                return self.attrs.get(expr.attr, 0)
            return 0
        if isinstance(expr, ast.Subscript):
            base = self.level(expr.value)
            return 2 if base else 0   # element of a device container
        if isinstance(expr, ast.Call):
            return self._call_level(expr)
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            lv = max((self.level(e) for e in expr.elts), default=0)
            return 1 if lv else 0
        if isinstance(expr, ast.Dict):
            lv = max((self.level(v) for v in expr.values if v), default=0)
            return 1 if lv else 0
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            lv = self.level(expr.elt)
            # comprehension over a device container yields elements
            for gen in expr.generators:
                if self.level(gen.iter):
                    lv = max(lv, 2)
            return 1 if lv else 0
        if isinstance(expr, ast.IfExp):
            return max(self.level(expr.body), self.level(expr.orelse))
        if isinstance(expr, ast.BinOp):
            return max(self.level(expr.left), self.level(expr.right))
        if isinstance(expr, (ast.UnaryOp,)):
            return self.level(expr.operand)
        if isinstance(expr, ast.Starred):
            return self.level(expr.value)
        return 0

    def _call_level(self, call: ast.Call) -> int:
        head = dotted(call.func)
        if head in _LAUNDER_HEADS:
            return 0
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _LAUNDER_METHODS:
                return 0
            if call.func.attr in _CONTAINER_GETTERS:
                return self.level(call.func.value)
            if call.func.attr.endswith(_DEVICE_CALL_SUFFIXES):
                return 2
        if head:
            if head in _DEVICE_EXACT:
                return 2
            if head.startswith(_DEVICE_HEAD_PREFIXES):
                return 2
        # unknown call: containers/arrays flow through (iter/next/list)
        lv = max((self.level(a) for a in call.args), default=0)
        return lv


class _MethodInfo:
    def __init__(self, node):
        self.node = node
        self.calls: Set[str] = set()

    def collect_calls(self):
        for sub in ast.walk(self.node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == "self":
                self.calls.add(sub.func.attr)


def _local_taint(fn_node, attrs: Dict[str, int]) -> Dict[str, int]:
    """Fixed-point device level of local names: only BARE-name targets
    are tainted (`cache.k, v = devcall()` taints nothing local — the
    attribute store is the cache object's business, not this scope's)."""
    local: Dict[str, int] = {}
    changed = True
    while changed:
        changed = False
        tt = _Taint(local, attrs)
        for sub in ast.walk(fn_node):
            pairs = []
            if isinstance(sub, ast.Assign):
                lv = tt.level(sub.value)
                if lv:
                    for t in sub.targets:
                        pairs.extend((n, lv) for n in _bare_names(t))
            elif isinstance(sub, ast.For):
                lv = tt.level(sub.iter)
                if lv:
                    # iterating a device container binds elements
                    pairs.extend((n, 2 if lv == 1 else lv)
                                 for n in _bare_names(sub.target))
            for name, lv in pairs:
                if local.get(name, 0) < lv:
                    local[name] = lv
                    changed = True
    return local


def _bare_names(target) -> List[str]:
    out = []
    if isinstance(target, ast.Name):
        out.append(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            out.extend(_bare_names(e))
    return out


def _attr_fixpoint(methods: Dict[str, _MethodInfo]) -> Dict[str, int]:
    attrs: Dict[str, int] = {}
    changed = True
    while changed:
        changed = False
        for mi in methods.values():
            local = _local_taint(mi.node, attrs)
            tt = _Taint(local, attrs)
            for sub in ast.walk(mi.node):
                updates = []
                if isinstance(sub, ast.Assign):
                    lv = tt.level(sub.value)
                    if lv:
                        for t in sub.targets:
                            for name, via_sub in _self_attr_targets(t):
                                # storing INTO self.X[...] makes X a
                                # container of device values
                                updates.append((name, 1 if via_sub
                                                else lv))
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in ("append", "appendleft", "add",
                                          "extend", "insert"):
                    names = [n for n, _ in
                             _self_attr_targets(sub.func.value)]
                    if names and any(tt.level(a) for a in sub.args):
                        updates.extend((n, 1) for n in names)
                for name, lv in updates:
                    if attrs.get(name, 0) < lv:
                        attrs[name] = lv
                        changed = True
    return attrs


def _self_attr_targets(node) -> List:
    """[(attr_name, via_subscript)] for self.X / self.X[...] targets."""
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            out.extend(_self_attr_targets(e))
        return out
    via_sub = False
    while isinstance(node, ast.Subscript):
        node = node.value
        via_sub = True
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        out.append((node.attr, via_sub))
    return out


def _reachable(methods: Dict[str, _MethodInfo]) -> Dict[str, List[str]]:
    """method -> shortest call chain from a hot entry point. `step` is
    the preferred root (chains read "step -> _dispatch_chunk"); any
    dispatch/collect method it doesn't reach seeds its own chain."""
    chains: Dict[str, List[str]] = {}

    def bfs(roots):
        frontier = list(roots)
        while frontier:
            nxt = []
            for name in frontier:
                for callee in sorted(methods[name].calls):
                    if callee in methods and callee not in chains:
                        chains[callee] = chains[name] + [callee]
                        nxt.append(callee)
            frontier = nxt

    roots = [n for n in _ENTRY_NAMES if n in methods]
    for n in roots:
        chains[n] = [n]
    bfs(roots)
    extra = [n for n in methods
             if n.startswith(_ENTRY_PREFIXES) and n not in chains]
    for n in extra:
        chains[n] = [n]
    bfs(extra)
    return chains


def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {n.name: _MethodInfo(n) for n in cls.body
                   if isinstance(n, FuncNode)}
        # serving-scheduler shape only: a bare `step` (optimizers etc.)
        # is not a dispatch pipeline
        if "step" not in methods or not any(
                m.startswith(("_dispatch", "_collect"))
                for m in methods):
            continue
        for mi in methods.values():
            mi.collect_calls()
        attrs = _attr_fixpoint(methods)
        for name, chain in _reachable(methods).items():
            mi = methods[name]
            tt = _Taint(_local_taint(mi.node, attrs), attrs)
            findings.extend(_scan_sinks(
                mi.node, tt, ctx, f"{cls.name}.{name}",
                " -> ".join(chain)))
    return findings


def _scan_sinks(fn_node, tt: _Taint, ctx: FileContext, qual: str,
                chain: str) -> List[Finding]:
    out: List[Finding] = []
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Call):
            head = dotted(sub.func)
            if head in _SINK_WHOLE and sub.args and \
                    tt.level(sub.args[0]) >= 1:
                out.append(Finding(
                    ctx.path, sub.lineno, "FC301",
                    f"`{head}` on a device value inside the serving "
                    f"hot path blocks the host on the device; keep "
                    f"syncs at the designed collection points", qual,
                    chain))
            elif head in _SINK_CASTS and sub.args and \
                    tt.level(sub.args[0]) >= 2:
                out.append(Finding(
                    ctx.path, sub.lineno, "FC301",
                    f"`{head}()` on a device value inside the serving "
                    f"hot path forces a blocking transfer", qual,
                    chain))
            elif isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _SINK_METHODS and \
                    tt.level(sub.func.value) >= 2:
                out.append(Finding(
                    ctx.path, sub.lineno, "FC301",
                    f"`.{sub.func.attr}()` on a device value inside "
                    f"the serving hot path blocks the host", qual,
                    chain))
        elif isinstance(sub, (ast.If, ast.While)):
            # implicit __bool__ of a device ARRAY (`if x:`); container
            # truthiness (`if self._inflight:`) is host-side and fine
            t = sub.test
            if isinstance(t, (ast.Name, ast.Attribute, ast.Subscript)) \
                    and tt.level(t) >= 2:
                out.append(Finding(
                    ctx.path, sub.lineno, "FC301",
                    "implicit `bool()` of a device value (`if x:`) "
                    "inside the serving hot path is a hidden blocking "
                    "sync", qual, chain))
    return out


def setup(register):
    register("host_sync", check, {
        "FC301": "blocking host sync on a device value in the hot path",
    })
