"""Donation/aliasing rule (FC501): use of an argument after donating it.

Hazard: ``jax.jit(f, donate_argnums=...)`` lets XLA reuse the donated
operand's buffer for an output — the serving engine donates the KV pool
into every prefill/decode dispatch precisely so the multi-GiB cache is
updated in place (``serving.py``: ``jax.jit(prefill, donate_argnums=(1,
2))``). After the call the donated buffer is DELETED: reading the old
Python reference raises "Array has been deleted" at best, and on some
backends silently reads clobbered memory. The safe idiom — the one this
repo uses everywhere — immediately rebinds the donated reference to the
returned value in the same statement: ``toks, cache.k, cache.v =
self._prefill_j(..., cache.k, cache.v, ...)``.

Mechanics: we map jit-wrapped callables to their donated positions from
``X = jax.jit(f, donate_argnums=...)`` assignments (including
``self._x = ...``) and ``@partial(jax.jit, donate_argnums=...)``
decorations, then at every call site check whether a donated argument
expression (a name or dotted attribute) is read again later in the
enclosing function before being stored — including the implicit re-read
on the next iteration when the call sits in a loop.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FileContext
from .scopes import (FuncNode, dotted, func_of_map,
                     literal_int_collection, tail_of, unwrap_partial)


def _donate_nums(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            vals = literal_int_collection(kw.value) or []
            return {v for v in vals if isinstance(v, int)}
    return set()


def _jit_target(call: ast.Call) -> Optional[ast.Call]:
    """The jit(...) call node, unwrapping partial(jax.jit, ...)."""
    if tail_of(dotted(call.func)) in ("jit", "pjit"):
        return call
    inner = unwrap_partial(call)
    if inner is not None and \
            tail_of(dotted(inner.func)) in ("jit", "pjit"):
        return inner
    return None


def _collect_donating(tree: ast.Module) -> Dict[str, Set[int]]:
    """dotted callee name -> donated positional indices.

    Names are as they appear at call sites: 'self._prefill_j' for
    `self._prefill_j = jax.jit(...)`, bare 'step_fn' for a decorated
    def or local assignment."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            jit = _jit_target(node.value)
            if jit is None:
                continue
            nums = _donate_nums(jit)
            if not nums:
                continue
            for t in node.targets:
                name = dotted(t)
                if name:
                    out[name] = nums
        elif isinstance(node, FuncNode):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    jit = _jit_target(dec)
                    if jit is not None:
                        nums = _donate_nums(jit)
                        if nums:
                            out[node.name] = nums
    return out


def _stmt_sequence(fn_node):
    """(flat source-ordered statements, branch map) of a function body
    (not descending into nested defs). The branch map gives each
    statement its set of (if-node-id, arm) memberships so two
    statements in MUTUALLY EXCLUSIVE arms of the same `if` are never
    treated as sequential."""
    out: List[ast.stmt] = []
    branch: Dict[int, frozenset] = {}

    def walk(stmts, arms: frozenset):
        for st in stmts:
            if isinstance(st, FuncNode + (ast.ClassDef,)):
                continue
            out.append(st)
            branch[id(st)] = arms
            if isinstance(st, ast.If):
                walk(st.body, arms | {(id(st), 0)})
                walk(st.orelse, arms | {(id(st), 1)})
            else:
                for field in ("body", "orelse", "finalbody"):
                    walk(getattr(st, field, []) or [], arms)
                for h in getattr(st, "handlers", []) or []:
                    walk(h.body, arms)

    walk(fn_node.body, frozenset())
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out, branch


def _exclusive(branch, a: ast.stmt, b: ast.stmt) -> bool:
    """True when a and b sit in different arms of the same if."""
    arms_a = dict(branch.get(id(a), frozenset()))
    for if_id, arm in branch.get(id(b), frozenset()):
        if if_id in arms_a and arms_a[if_id] != arm:
            return True
    return False


def _reads_of(expr_path: str, node: ast.AST) -> List[ast.AST]:
    hits = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)) and \
                dotted(sub) == expr_path and \
                isinstance(getattr(sub, "ctx", ast.Load()), ast.Load):
            hits.append(sub)
    return hits


def _stores_of(expr_path: str, st: ast.stmt) -> bool:
    targets = []
    if isinstance(st, ast.Assign):
        targets = st.targets
    elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
        targets = [st.target]
    elif isinstance(st, ast.For):
        targets = [st.target]
    for t in targets:
        stack = [t]
        while stack:
            x = stack.pop()
            if isinstance(x, (ast.Tuple, ast.List)):
                stack.extend(x.elts)
            elif dotted(x) == expr_path:
                return True
    return False


def check(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    donating = _collect_donating(tree)
    if not donating:
        return []
    findings: List[Finding] = []
    owner_of = func_of_map(tree)

    for fn in [n for n in ast.walk(tree) if isinstance(n, FuncNode)]:
        seq, branch = _stmt_sequence(fn)
        loops = [n for n in ast.walk(fn)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        seen = set()
        for idx, st in enumerate(seq):
            for call in _own_calls(st):
                name = dotted(call.func)
                nums = donating.get(name or "")
                if not nums:
                    continue
                for pos in sorted(nums):
                    if pos >= len(call.args):
                        continue
                    path = dotted(call.args[pos])
                    if not path:
                        continue  # non-name donated expr (literal/call)
                    for f in _check_use_after(
                            ctx, owner_of.get(st, fn.name), name, path,
                            st, idx, seq, branch, loops):
                        key = (f.line, f.message)
                        if key not in seen:
                            seen.add(key)
                            findings.append(f)
    return findings


def _check_use_after(ctx, qual, callee, path, call_st, idx, seq, branch,
                     loops):
    out: List[Finding] = []
    # the call's own statement: a store there (tuple-assign of results
    # back onto the donated ref) re-binds BEFORE any later read
    if _stores_of(path, call_st):
        return out
    # later statements in source order: read-before-store => bug.
    # statements in the opposite arm of the call's `if` never execute
    # on the same path and are skipped.
    for later in seq[idx + 1:]:
        if _exclusive(branch, call_st, later):
            continue
        if _stores_of(path, later):
            # a store can appear in the same statement as a read
            # (x = f(x)) — that read is of the NEW value; stop either way
            break
        reads = _reads_of(path, later)
        if reads:
            out.append(Finding(
                ctx.path, later.lineno, "FC501",
                f"'{path}' is read after being donated to "
                f"'{callee}' (line {call_st.lineno}); the buffer is "
                f"deleted by donation — rebind it from the call's "
                f"result or drop donate_argnums", qual))
            return out
    # loop wrap-around: call inside a loop, donated ref never stored in
    # that loop body => next iteration re-reads a deleted buffer
    for loop in loops:
        if _contains(loop, call_st):
            stored = any(_stores_of(path, st) for st in _body_stmts(loop))
            if not stored:
                out.append(Finding(
                    ctx.path, call_st.lineno, "FC501",
                    f"'{path}' is donated to '{callee}' inside a loop "
                    f"but never rebound in the loop body — the next "
                    f"iteration passes a deleted buffer", qual))
            break
    return out


def _own_calls(st: ast.stmt):
    """Call nodes belonging to THIS statement — for compound statements
    only the header expression (test/iter/items), so a call inside the
    body is attributed to its own (innermost) statement in the
    sequence, not to every enclosing compound."""
    if isinstance(st, (ast.If, ast.While)):
        exprs = [st.test]
    elif isinstance(st, (ast.For, ast.AsyncFor)):
        exprs = [st.iter]
    elif isinstance(st, (ast.With, ast.AsyncWith)):
        exprs = [i.context_expr for i in st.items]
    elif isinstance(st, ast.Try):
        exprs = []
    else:
        exprs = [st]
    for e in exprs:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call):
                yield sub


def _body_stmts(loop):
    out = []
    stack = list(loop.body)
    while stack:
        st = stack.pop()
        if isinstance(st, FuncNode + (ast.ClassDef,)):
            continue
        out.append(st)
        for field in ("body", "orelse", "finalbody"):
            stack.extend(getattr(st, field, []) or [])
    return out


def _contains(outer, target) -> bool:
    return any(sub is target for sub in ast.walk(outer))


def setup(register):
    register("donation", check, {
        "FC501": "argument read after being passed in a donated position",
    })
