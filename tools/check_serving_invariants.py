#!/usr/bin/env python
"""Serving-path checker gate: RUNTIME invariants + STATIC analysis +
CHAOS in one entry point (ISSUE 1 satellite; extended for the ISSUE 2
chunked-prefill schedules; ISSUE 3 added the flightcheck static half;
ISSUE 4 added the fault-tolerance tests and the deterministic chaos
phase).

Phase 1 — static: runs the flightcheck suite (tools/flightcheck) over
``paddle_tpu/inference/`` — tracer safety, recompilation hazards,
hot-path host syncs, PRNG discipline, donation aliasing. Zero cost, no
devices; catches the hazard classes no runtime assertion can (they
don't fail, they just serve slowly or sample wrongly).

Phase 2 — runtime: runs the serving-path test files with
PADDLE_TPU_POOL_DEBUG=1, which makes ServingEngine.step() call
PagedKVCache.debug_check() after every scheduler iteration — asserting
the pool invariant

    free + cached + referenced == num_blocks

plus ref-count/table consistency (no leak, no double free), the
hash-index bijection, and the partial-prefill length bound (a chunked
prefill extends a sequence over several scheduler steps; its context
length must sit inside the blocks reserved at admission BETWEEN every
pair of chunks — test_chunked_prefill.py drives multi-chunk prompts,
mid-stream admissions, splice-pending dependencies, and eviction
pressure through that window). Exit code is non-zero when EITHER phase
fails.

    python tools/check_serving_invariants.py            # both phases
    python tools/check_serving_invariants.py -k prefix  # pass-through
"""
from __future__ import annotations

import os
import sys

os.environ["PADDLE_TPU_POOL_DEBUG"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TEST_FILES = [
    os.path.join(REPO, "tests", "test_prefix_cache.py"),
    os.path.join(REPO, "tests", "test_chunked_prefill.py"),
    os.path.join(REPO, "tests", "test_serving.py"),
    os.path.join(REPO, "tests", "test_fault_tolerance.py"),
    os.path.join(REPO, "tests", "test_ragged_batching.py"),
    os.path.join(REPO, "tests", "test_tp_serving.py"),
    os.path.join(REPO, "tests", "test_spec_decode.py"),
    os.path.join(REPO, "tests", "test_lora_serving.py"),
    os.path.join(REPO, "tests", "test_fleet_serving.py"),
    os.path.join(REPO, "tests", "test_transport_fleet.py"),
    os.path.join(REPO, "tests", "test_telemetry.py"),
    os.path.join(REPO, "tests", "test_kv_quant.py"),
    os.path.join(REPO, "tests", "test_program_observatory.py"),
    os.path.join(REPO, "tests", "test_multi_step.py"),
    os.path.join(REPO, "tests", "test_flightcheck.py"),
    os.path.join(REPO, "tests", "test_mem_audit.py"),
]


def run_flightcheck() -> int:
    """Static phase: flightcheck over the WHOLE package (ISSUE 7 widened
    the former inference/-only scope — the FC6xx sharding family gates
    distributed/ and the models too; ISSUE 18 added the FC7xx memory
    family), plus the comm audit (distributed entry points' collectives
    vs committed per-program expectations) and the mem audit (the same
    entry points' argument/output/peak-temp/donated bytes vs
    tools/flightcheck/mem_expectations.json)."""
    from tools.flightcheck import DEFAULT_BASELINE, core
    target = os.path.join(REPO, "paddle_tpu")
    new, old = core.run(target, DEFAULT_BASELINE)
    for f in new:
        print(core.format_finding(f))
    rc = 0
    if new:
        print(f"FLIGHTCHECK GATE FAILED — {len(new)} new finding(s) in "
              f"paddle_tpu/")
        rc = 1
    else:
        print(f"FLIGHTCHECK OK — paddle_tpu/ clean "
              f"({len(old)} baselined)")
    import subprocess
    if os.environ.get("FLIGHTCHECK_COMM_AUDIT_RAN") == "1":
        # run_checks.sh already ran the audit as its own phase; don't
        # trace all 14 distributed programs twice per gate run
        print("COMM AUDIT skipped — already run by the caller")
        comm_rc = 0
    else:
        comm_rc = subprocess.call(
            [sys.executable, "-m", "tools.flightcheck.comm_audit"],
            cwd=REPO)
        print("COMM AUDIT OK — collectives match expectations"
              if comm_rc == 0 else
              f"COMM AUDIT GATE FAILED (exit {comm_rc})")
    if os.environ.get("FLIGHTCHECK_MEM_AUDIT_RAN") == "1":
        print("MEM AUDIT skipped — already run by the caller")
        mem_rc = 0
    else:
        mem_rc = subprocess.call(
            [sys.executable, "-m", "tools.flightcheck.mem_audit"],
            cwd=REPO)
        print("MEM AUDIT OK — per-program bytes match expectations"
              if mem_rc == 0 else
              f"MEM AUDIT GATE FAILED (exit {mem_rc})")
    return rc or comm_rc or mem_rc


def run_chaos() -> int:
    """Chaos phase (ISSUE 4; ISSUE 5 added the ragged leg): a short
    DETERMINISTIC fault-injection schedule — seeded OOMs, dispatch
    faults, collect faults and cancellations over an
    optimistically-admitted engine — asserting debug_check after every
    step and token identity of every surviving request vs a fault-free
    replay. --require-events guarantees each gate run exercised at
    least one OOM-driven preemption, one injected dispatch failure and
    one cancellation. The schedule runs TWICE: once on the dense path
    and once with ragged=True, so preemption row-range neutralize,
    cancel-driven reader restarts and dispatch-fault recovery are
    exercised on the unified one-program-per-step scheduler too.
    ISSUE 8 added the --tp 2 leg: the same schedule on the
    tensor-parallel shard_map engine — preemption neutralization,
    epoch guards and retry must stay request-granular under
    sharding. ISSUE 9 added the --spec leg: n-gram drafts ride the
    verify program through the whole fault schedule, and
    --require-events demands >=1 draft rejection on top of the
    preemption/fault/cancel events, so the rejected-tail
    KV/position rollback is exercised with faults in flight.
    ISSUE 10 added the --lora leg: multi-tenant traffic over a
    3-adapter registry (some requests masked via allowed_tokens) —
    --require-events additionally demands >=1 adapter eviction-
    and-refault and >=1 masked decode column, so S-LoRA paging
    churns under the same faults. ISSUE 11 added the --dp 2 leg:
    the same schedule through a 2-replica prefix-affinity fleet
    Router with replica 0 WEDGED at a seeded mid-run step —
    --require-events demands >=1 replica failover and >=1
    migrated-request completion, and token identity covers
    surviving AND migrated requests vs a fault-free fleet replay
    (the router drains the wedged replica and redistributes its
    queue as no-sample prompt+history recomputes). ISSUE 19 added
    the --dp-transport process leg (dp_proc): the same schedule
    through a PROCESS-PER-REPLICA fleet whose replica-0 worker
    SIGKILLs itself at a seeded mid-run step while parent-side
    monkeys drop/delay RPCs — --require-events additionally demands
    >=1 worker exit, >=1 supervisor respawn and >=1 retried RPC, the
    fault-free replay runs INPROC so token identity also proves the
    transport is token-neutral, and the sealed-programs assertion
    covers the respawned worker's replayed warmup+seal (0 unexpected
    recompiles after re-seal)."""
    import shutil
    import subprocess
    import tempfile
    rc_all = 0
    trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_chaos_trace_")
    print(f"CHAOS flight-recorder exports: {trace_dir}/chaos_<leg>"
          f".trace.json (kept on failure, removed on a green run)")
    # the lora leg (ISSUE 10) runs more requests on a 20-block pool:
    # the two knobs that make a previously-resident adapter actually
    # get EVICTED and refaulted mid-schedule (--require-events demands
    # it) without tipping the oldest-runner preemption cycle into the
    # no-progress regime a 14-block pool + 9 adapter pages produces.
    # ISSUE 12: every leg runs with serving telemetry ON and writes
    # its flight-recorder export next to the log; the dp2 leg's trace
    # is then VALIDATED (parses, carries >= 1 span per lifecycle
    # phase, and shows a migrated request as ONE continuous span
    # crossing two replica tracks).
    # ISSUE 13: the ragged leg RE-RUNS on the quantized KV pool
    # (ragged_kv8) — same seeded schedule, int8 planes + sidecar
    # scales, debug_check through every rollback/eviction, token
    # identity vs a fault-free replay on the SAME quantized pool.
    # ISSUE 14: every leg runs --seal-programs — the chaos engine's
    # reachable program grid is compiled and SEALED before traffic,
    # so a schedule path that provokes a mid-run XLA retrace (the
    # runtime FC2xx) fails its leg via unexpected_recompiles != 0;
    # the dp2 trace is additionally validated for counter-track
    # schema and >= 1 compile span (validate_trace below)
    # ISSUE 16: the ragged_ms4 leg re-runs the schedule with
    # multi_step=4 — k serving steps fused into ONE device program.
    # Every OOM preemption neutralizes a whole fused window, every
    # cancellation lands at a k-boundary, debug_check runs per
    # boundary, and --require-events additionally demands >= 1 fused
    # window actually dispatched (multi_step_windows >= 1).
    for tag, leg in (("dense", ()), ("ragged", ("--ragged",)),
                     ("ragged_kv8", ("--ragged", "--kv-quant", "int8")),
                     ("tp2", ("--tp", "2")), ("spec", ("--spec",)),
                     ("lora", ("--lora", "--num-blocks", "20",
                               "--requests", "12")),
                     ("dp2", ("--dp", "2")),
                     ("dp_proc", ("--dp", "2", "--dp-transport",
                                  "process")),
                     ("ragged_ms4", ("--ragged", "--multi-step", "4"))):
        trace_path = os.path.join(trace_dir, f"chaos_{tag}.trace.json")
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "chaos_serving.py"),
               "--steps", "60", "--requests", "8", "--require-events",
               "--seal-programs", "--trace-out", trace_path, *leg]
        rc = subprocess.call(cmd)
        print(f"CHAOS GATE ({tag}) OK — fault schedule survived, "
              "outputs identical" if rc == 0
              else f"CHAOS GATE ({tag}) FAILED (exit {rc}; "
                   f"flight recorder: {trace_path})")
        rc_all = rc_all or rc
    trc = validate_trace(os.path.join(trace_dir, "chaos_dp2.trace.json"))
    rc_all = rc_all or trc
    if rc_all == 0:
        # a fully green run needs no post-mortems — don't let repeated
        # gate runs accumulate orphaned trace directories in /tmp
        shutil.rmtree(trace_dir, ignore_errors=True)
    return rc_all


def validate_trace(path: str) -> int:
    """Telemetry gate (ISSUE 12): the dp2 chaos leg's exported trace
    must parse as Chrome-trace JSON, carry at least one span for every
    lifecycle phase the leg exercises (queued / prefill / decode), a
    migrate event, and at least one trace id whose phase slices land on
    TWO OR MORE replica pids with exactly one begin/end pair — the
    migrated request rendering as a single continuous span crossing
    replicas in Perfetto. ISSUE 14 adds the program-observatory
    schema: at least one ``compile`` span (the grid warmup runs
    traced), and the counter tracks — every ``ph:"C"`` event carries a
    numeric ``args.value`` and each (pid, name) track's timestamps are
    monotonically non-decreasing, so Perfetto renders them as
    well-formed resource timelines."""
    import json
    from collections import defaultdict
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"TRACE GATE FAILED — cannot parse {path}: {e}")
        return 1
    evts = doc.get("traceEvents", [])
    problems = []
    for e in evts:
        for field in ("ph", "ts", "pid", "tid"):
            if field not in e:
                problems.append(f"event missing {field}: {e}")
                break
        if e.get("ph") == "X" and "dur" not in e:
            problems.append(f"X event missing dur: {e}")
    span_names = {e["name"] for e in evts if e.get("ph") == "X"}
    for phase in ("queued", "prefill", "decode"):
        if phase not in span_names:
            problems.append(f"no '{phase}' span in the trace")
    if not any(e.get("ph") == "i" and e["name"] == "migrate"
               for e in evts):
        problems.append("no migrate event in the dp2 trace")
    span_pids = defaultdict(set)
    for e in evts:
        if e.get("ph") == "X" and e.get("tid"):
            span_pids[e["tid"]].add(e["pid"])
    crossing = [t for t, pids in span_pids.items() if len(pids) >= 2]
    if not crossing:
        problems.append("no request span crosses two replica pids")
    # -- program observatory schema (ISSUE 14) --------------------------
    if "compile" not in span_names:
        problems.append("no compile span in the trace (the sealed "
                        "grid warmup runs traced)")
    track_ts = defaultdict(list)
    for e in evts:
        if e.get("ph") != "C":
            continue
        v = e.get("args", {}).get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"counter event without numeric value: {e}")
            continue
        track_ts[(e["pid"], e["name"])].append(e["ts"])
    if not track_ts:
        problems.append("no counter-track (ph:'C') events in the trace")
    for (pid, name), ts in track_ts.items():
        if any(b < a for a, b in zip(ts, ts[1:])):
            problems.append(f"counter track ({pid}, {name}) has "
                            f"decreasing timestamps")
    for t in crossing:
        b = sum(1 for e in evts if e.get("ph") == "b"
                and e.get("id") == str(t))
        en = sum(1 for e in evts if e.get("ph") == "e"
                 and e.get("id") == str(t))
        if (b, en) != (1, 1):
            problems.append(
                f"migrated trace {t} has {b} begin / {en} end events "
                f"(must be exactly one pair — one continuous span)")
    if problems:
        for p in problems[:8]:
            print(f"  trace problem: {p}")
        print(f"TRACE GATE FAILED — {len(problems)} problem(s) in "
              f"{path}")
        return 1
    print(f"TRACE GATE OK — dp2 flight recorder valid "
          f"({len(evts)} events, {len(crossing)} migrated span(s) "
          f"crossing replicas, {len(track_ts)} counter track(s)): "
          f"{path}")
    return 0


def main() -> int:
    static_rc = run_flightcheck()
    chaos_rc = run_chaos()
    import pytest
    args = TEST_FILES + ["-q", "-m", "not slow", "-p", "no:cacheprovider",
                         "-p", "no:randomly"] + sys.argv[1:]
    rc = pytest.main(args)
    print(("POOL INVARIANTS OK — debug_check ran after every "
           "engine step") if rc == 0 else
          f"POOL INVARIANT GATE FAILED (pytest exit {rc})")
    return int(rc) or static_rc or chaos_rc


if __name__ == "__main__":
    sys.exit(main())
