#!/usr/bin/env python
"""Serving-pool invariant gate (ISSUE 1 satellite; extended for the
ISSUE 2 chunked-prefill schedules).

Runs the serving-path test files with PADDLE_TPU_POOL_DEBUG=1, which
makes ServingEngine.step() call PagedKVCache.debug_check() after every
scheduler iteration — asserting the pool invariant

    free + cached + referenced == num_blocks

plus ref-count/table consistency (no leak, no double free), the
hash-index bijection, and the partial-prefill length bound (a chunked
prefill extends a sequence over several scheduler steps; its context
length must sit inside the blocks reserved at admission BETWEEN every
pair of chunks — test_chunked_prefill.py drives multi-chunk prompts,
mid-stream admissions, splice-pending dependencies, and eviction
pressure through that window). Exit code is pytest's: non-zero means a
test failed OR an invariant tripped mid-schedule.

    python tools/check_serving_invariants.py            # all files
    python tools/check_serving_invariants.py -k prefix  # pass-through
"""
from __future__ import annotations

import os
import sys

os.environ["PADDLE_TPU_POOL_DEBUG"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TEST_FILES = [
    os.path.join(REPO, "tests", "test_prefix_cache.py"),
    os.path.join(REPO, "tests", "test_chunked_prefill.py"),
    os.path.join(REPO, "tests", "test_serving.py"),
]


def main() -> int:
    import pytest
    args = TEST_FILES + ["-q", "-m", "not slow", "-p", "no:cacheprovider",
                         "-p", "no:randomly"] + sys.argv[1:]
    rc = pytest.main(args)
    print(("POOL INVARIANTS OK — debug_check ran after every "
           "engine step") if rc == 0 else
          f"POOL INVARIANT GATE FAILED (pytest exit {rc})")
    return int(rc)


if __name__ == "__main__":
    sys.exit(main())
