#!/usr/bin/env python
"""Serving-path checker gate: RUNTIME invariants + STATIC analysis +
CHAOS in one entry point (ISSUE 1 satellite; extended for the ISSUE 2
chunked-prefill schedules; ISSUE 3 added the flightcheck static half;
ISSUE 4 added the fault-tolerance tests and the deterministic chaos
phase).

Phase 1 — static: runs the flightcheck suite (tools/flightcheck) over
``paddle_tpu/inference/`` — tracer safety, recompilation hazards,
hot-path host syncs, PRNG discipline, donation aliasing. Zero cost, no
devices; catches the hazard classes no runtime assertion can (they
don't fail, they just serve slowly or sample wrongly).

Phase 2 — runtime: runs the serving-path test files with
PADDLE_TPU_POOL_DEBUG=1, which makes ServingEngine.step() call
PagedKVCache.debug_check() after every scheduler iteration — asserting
the pool invariant

    free + cached + referenced == num_blocks

plus ref-count/table consistency (no leak, no double free), the
hash-index bijection, and the partial-prefill length bound (a chunked
prefill extends a sequence over several scheduler steps; its context
length must sit inside the blocks reserved at admission BETWEEN every
pair of chunks — test_chunked_prefill.py drives multi-chunk prompts,
mid-stream admissions, splice-pending dependencies, and eviction
pressure through that window). Exit code is non-zero when EITHER phase
fails.

    python tools/check_serving_invariants.py            # both phases
    python tools/check_serving_invariants.py -k prefix  # pass-through
"""
from __future__ import annotations

import os
import sys

os.environ["PADDLE_TPU_POOL_DEBUG"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TEST_FILES = [
    os.path.join(REPO, "tests", "test_prefix_cache.py"),
    os.path.join(REPO, "tests", "test_chunked_prefill.py"),
    os.path.join(REPO, "tests", "test_serving.py"),
    os.path.join(REPO, "tests", "test_fault_tolerance.py"),
    os.path.join(REPO, "tests", "test_ragged_batching.py"),
    os.path.join(REPO, "tests", "test_tp_serving.py"),
    os.path.join(REPO, "tests", "test_spec_decode.py"),
    os.path.join(REPO, "tests", "test_lora_serving.py"),
    os.path.join(REPO, "tests", "test_fleet_serving.py"),
]


def run_flightcheck() -> int:
    """Static phase: flightcheck over the WHOLE package (ISSUE 7 widened
    the former inference/-only scope — the FC6xx sharding family gates
    distributed/ and the models too), plus the comm audit: the
    distributed entry points' collectives must match the committed
    per-program expectations (kind/axis/bytes/count)."""
    from tools.flightcheck import DEFAULT_BASELINE, core
    target = os.path.join(REPO, "paddle_tpu")
    new, old = core.run(target, DEFAULT_BASELINE)
    for f in new:
        print(core.format_finding(f))
    rc = 0
    if new:
        print(f"FLIGHTCHECK GATE FAILED — {len(new)} new finding(s) in "
              f"paddle_tpu/")
        rc = 1
    else:
        print(f"FLIGHTCHECK OK — paddle_tpu/ clean "
              f"({len(old)} baselined)")
    if os.environ.get("FLIGHTCHECK_COMM_AUDIT_RAN") == "1":
        # run_checks.sh already ran the audit as its own phase; don't
        # trace all 14 distributed programs twice per gate run
        print("COMM AUDIT skipped — already run by the caller")
        return rc
    import subprocess
    comm_rc = subprocess.call(
        [sys.executable, "-m", "tools.flightcheck.comm_audit"],
        cwd=REPO)
    print("COMM AUDIT OK — collectives match expectations"
          if comm_rc == 0 else
          f"COMM AUDIT GATE FAILED (exit {comm_rc})")
    return rc or comm_rc


def run_chaos() -> int:
    """Chaos phase (ISSUE 4; ISSUE 5 added the ragged leg): a short
    DETERMINISTIC fault-injection schedule — seeded OOMs, dispatch
    faults, collect faults and cancellations over an
    optimistically-admitted engine — asserting debug_check after every
    step and token identity of every surviving request vs a fault-free
    replay. --require-events guarantees each gate run exercised at
    least one OOM-driven preemption, one injected dispatch failure and
    one cancellation. The schedule runs TWICE: once on the dense path
    and once with ragged=True, so preemption row-range neutralize,
    cancel-driven reader restarts and dispatch-fault recovery are
    exercised on the unified one-program-per-step scheduler too.
    ISSUE 8 added the --tp 2 leg: the same schedule on the
    tensor-parallel shard_map engine — preemption neutralization,
    epoch guards and retry must stay request-granular under
    sharding. ISSUE 9 added the --spec leg: n-gram drafts ride the
    verify program through the whole fault schedule, and
    --require-events demands >=1 draft rejection on top of the
    preemption/fault/cancel events, so the rejected-tail
    KV/position rollback is exercised with faults in flight.
    ISSUE 10 added the --lora leg: multi-tenant traffic over a
    3-adapter registry (some requests masked via allowed_tokens) —
    --require-events additionally demands >=1 adapter eviction-
    and-refault and >=1 masked decode column, so S-LoRA paging
    churns under the same faults. ISSUE 11 added the --dp 2 leg:
    the same schedule through a 2-replica prefix-affinity fleet
    Router with replica 0 WEDGED at a seeded mid-run step —
    --require-events demands >=1 replica failover and >=1
    migrated-request completion, and token identity covers
    surviving AND migrated requests vs a fault-free fleet replay
    (the router drains the wedged replica and redistributes its
    queue as no-sample prompt+history recomputes)."""
    import subprocess
    rc_all = 0
    # the lora leg (ISSUE 10) runs more requests on a 20-block pool:
    # the two knobs that make a previously-resident adapter actually
    # get EVICTED and refaulted mid-schedule (--require-events demands
    # it) without tipping the oldest-runner preemption cycle into the
    # no-progress regime a 14-block pool + 9 adapter pages produces
    for tag, leg in (("dense", ()), ("ragged", ("--ragged",)),
                     ("tp2", ("--tp", "2")), ("spec", ("--spec",)),
                     ("lora", ("--lora", "--num-blocks", "20",
                               "--requests", "12")),
                     ("dp2", ("--dp", "2"))):
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "chaos_serving.py"),
               "--steps", "60", "--requests", "8", "--require-events",
               *leg]
        rc = subprocess.call(cmd)
        print(f"CHAOS GATE ({tag}) OK — fault schedule survived, "
              "outputs identical" if rc == 0
              else f"CHAOS GATE ({tag}) FAILED (exit {rc})")
        rc_all = rc_all or rc
    return rc_all


def main() -> int:
    static_rc = run_flightcheck()
    chaos_rc = run_chaos()
    import pytest
    args = TEST_FILES + ["-q", "-m", "not slow", "-p", "no:cacheprovider",
                         "-p", "no:randomly"] + sys.argv[1:]
    rc = pytest.main(args)
    print(("POOL INVARIANTS OK — debug_check ran after every "
           "engine step") if rc == 0 else
          f"POOL INVARIANT GATE FAILED (pytest exit {rc})")
    return int(rc) or static_rc or chaos_rc


if __name__ == "__main__":
    sys.exit(main())
