"""Benchmark: Llama causal-LM training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value   = steady-state training tokens/sec/chip (compiled TrainStep,
          bf16 weights, AdamW with f32 masters)
vs_baseline = achieved_MFU / 0.40 (BASELINE.md north star: >=40% MFU).

MFU accounting follows the PaLM-appendix convention:
  flops/token = 6*N_params + 12*L*H*Q*S  (attention term)
Peak chip flops: v5e = 197e12 bf16, v5p = 459e12.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def detect_peak_flops() -> float:
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    # default: v5e / "TPU v5 lite"
    return 197e12


def run(config: str = "small"):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import (LlamaForCausalLM, llama_small, llama_tiny)

    paddle.seed(0)
    if config == "small":
        # Pallas flash attention keeps activations light → no remat needed;
        # measured best at batch 8 (72% MFU on v5e vs 61% with remat)
        cfg = llama_small(dtype="bfloat16", use_recompute=False)
        batch, seq, iters = 8, 1024, 10
    else:
        cfg = llama_tiny(dtype="bfloat16")
        batch, seq, iters = 8, 256, 10

    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                          weight_decay=0.01)
    step = paddle.jit.TrainStep(model, lambda o, l: model.loss(o, l), opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))

    # warmup/compile
    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    final = float(loss)  # blocks
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    n_params = model.num_params()
    l_, h_, q_ = (cfg.num_hidden_layers, cfg.num_attention_heads,
                  cfg.hidden_size // cfg.num_attention_heads)
    flops_per_token = 6 * n_params + 12 * l_ * h_ * q_ * seq
    mfu = tokens_per_sec * flops_per_token / detect_peak_flops()
    return {
        "metric": f"llama_{config}_train_tokens_per_sec_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "final_loss": round(final, 4),
            "step_ms": round(1000 * dt / iters, 2),
        },
    }


if __name__ == "__main__":
    config = sys.argv[1] if len(sys.argv) > 1 else "small"
    try:
        result = run(config)
    except Exception as e:  # OOM or compile failure: fall back to tiny
        if config == "small":
            sys.stderr.write(f"bench small failed ({e}); retrying tiny\n")
            result = run("tiny")
        else:
            raise
    print(json.dumps(result))
