"""Benchmark suite for one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Headline = Llama causal-LM training throughput (largest config that fits
the chip: llama_mid ~0.7B with GQA, fallback llama_small 0.5B), measured
as steady-state tokens/sec/chip with a compiled TrainStep (bf16 weights,
AdamW with f32 masters). vs_baseline = achieved_MFU / 0.40 (BASELINE.md
north star: >=40% MFU at Llama-3-8B class).

MFU accounting follows the PaLM-appendix convention:
  flops/token = 6*N_params + 12*L*H*Q*S  (attention term)
Peak chip flops: v5e = 197e12 bf16, v5p = 459e12.

Self-defense (r5, after the poisoned r4 capture): `auto` mode is a
JAX-free ORCHESTRATOR that runs every row in its own subprocess, so one
OOM cannot cascade through the suite, and brackets the run with a
known-FLOPs calibration matmul:
  - calibration preamble: a scanned bf16 4096^3 matmul must reach a
    plausible fraction of the chip's peak (>=25%); below that the
    environment (not the code) is broken -> retry with backoff, and if
    it never clears, emit {"env_suspect": true} + the calibration
    number INSTEAD of recording garbage perf rows.
  - per-mode isolation + retry: a failed/slow row is retried once in a
    fresh process after re-calibrating; a row that is still <30% of its
    last-known-good is recorded with a per-row "suspect" flag.
  - per-mode vs_baseline: every row reports value / last-known-good
    (the judge-verified r4 numbers), so single-mode driver runs track
    trends. The headline keeps its MFU/0.40 semantic; its LKG ratio is
    in extra.
The reference treats perf capture as gated CI infrastructure
(tools/ci_op_benchmark.sh:128-145 + check_op_benchmark_result.py); this
is the TPU-side equivalent.

Modes: `python bench.py [auto|mid|mid4k|mid8k|1b|small|tiny|resnet|
decode|serving|pp|moe|dit|calibrate]` — auto (the driver default)
orchestrates the full set: headline llama + long-context rows +
ResNet-50 + paged decode (bf16/int4) + the open-loop serving suite +
capacity row + shared-prefix cache A/B + pipeline engine + MoE
dense/ragged + DiT-XL/2.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Last-known-good table (r4 judge re-runs on the same v5e chip, plus r3
# captures where r4 has no number). Every mode's child emits
# extra["lkg_ratio"] = primary_value / LKG (inverted for lower-is-better
# metrics) so the parent can tell "code got slower" from "env is broken"
# and single-mode runs report a real trend ratio.
# ---------------------------------------------------------------------------
LKG = {
    #  mode: [(path into the child's result, value, lower_is_better)];
    #  the reported ratio is the MIN over resolvable entries, so modes
    #  whose primary value and health metric differ (serving's
    #  arrival-limited open-loop tok/s vs its capacity decode) gate on
    #  whichever regressed
    "mid":     [("value", 32859.0, False)],
    "mid4k":   [("extra.mfu", 0.740, False)],
    "mid8k":   [("extra.mfu", 0.760, False)],
    "1b":      [("extra.mfu", 0.703, False)],
    "small":   [("extra.mfu", 0.72, False)],
    "resnet":  [("value", 2170.0, False)],
    "decode":  [("value", 4434.0, False),
                ("extra.paged_decode_int4_tok_per_sec", 5604.0, False)],
    "8b":      [("value", 866.0, False),
                ("extra.paged_decode_8b_int8_tok_per_sec", 674.0,
                 False)],
    "serving": [("extra.serving_bf16_c8_tok_per_sec", 289.0, False),
                ("extra.serving_capacity_decode_tok_per_sec", 3398.0,
                 False)],
    "pp":      [("extra.pp_tick_fwd_ms", 0.086, True),
                ("extra.pp_tick_bwd_ms", 0.301, True)],
    "moe":     [("value", 66282.0, False),
                ("extra.moe_ragged_wide_mfu_activated", 0.585, False)],
    "dit":     [("extra.dit_xl2_mfu", 0.779, False)],
}

# serving_tp runs as its OWN auto mode (not only a serving-suite row):
# inside the suite the jax backend is already initialized by earlier
# rows, so ensure_devices(8) can only skip — a fresh subprocess lets it
# force the 8-CPU-device mesh before anything touches jax
AUTO_MODES = ("mid4k", "mid8k", "1b", "resnet", "decode", "8b",
              "serving", "serving_tp", "serving_lora", "serving_dp",
              "serving_proc", "serving_kv8", "serving_msteps", "pp",
              "moe", "dit", "profile")

MODE_TIMEOUT_S = {"serving": 3300, "decode": 2100, "8b": 3600}
DEFAULT_TIMEOUT_S = 1800

# calibration plausibility band: a big scanned bf16 matmul on an
# otherwise-idle chip lands 50-90% of peak; the r4 poisoned env ran 24x
# slow (~3-4%). >1.5 means the dispatch-diff timing itself collapsed.
CAL_BAND = (0.25, 1.5)


def detect_peak_flops() -> float:
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    # default: v5e / "TPU v5 lite"
    return 197e12


def _lkg_ratio(mode: str, result: dict):
    """value-vs-last-known-good for a finished child result: the min
    ratio over the mode's LKG entries (None when the mode has no entry
    or none of the paths resolve)."""
    ratios = []
    for path, lkg, lower in LKG.get(mode, ()):
        node = result
        for part in path.split("."):
            if not isinstance(node, dict) or part not in node:
                node = None
                break
            node = node[part]
        if isinstance(node, (int, float)) and node > 0:
            ratios.append(lkg / node if lower else node / lkg)
    return round(min(ratios), 4) if ratios else None


def run_calibration():
    """Known-FLOPs sanity probe (VERDICT r4 weak#1): a scanned bf16
    square matmul whose achieved FLOP/s must land in a plausible band
    for the detected chip. Uses the dispatch-diff timer so the tunnel
    RTT cancels. On CPU (tests) the band check is skipped — there is no
    trustworthy CPU peak number."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.utils.timing import timed_dispatch_diff

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    n, iters = (4096, 32) if on_tpu else (256, 4)
    x = jnp.full((n, n), 1.0 / n, jnp.bfloat16)

    def many(a):
        def body(c, _):
            return (c @ a) * 2.0, None
        y, _ = jax.lax.scan(body, a, None, length=iters)
        # scalar return: fetching the full [n, n] product through the
        # tunnel costs more (and varies more) than the matmuls being
        # timed, which collapses the dispatch diff
        return jnp.sum(y.astype(jnp.float32))

    f = jax.jit(many)
    sec_per_iter = timed_dispatch_diff(f, (x,), calls=(1, 3), repeats=3,
                                       per_call=iters)
    achieved = 2.0 * n ** 3 / sec_per_iter
    out = {
        "calibration_tflops": round(achieved / 1e12, 2),
        "calibration_platform": platform,
        "calibration_device": getattr(jax.devices()[0], "device_kind",
                                      str(jax.devices()[0])),
    }
    if on_tpu:
        frac = achieved / detect_peak_flops()
        out["calibration_frac_peak"] = round(frac, 4)
        out["calibration_ok"] = bool(CAL_BAND[0] <= frac <= CAL_BAND[1])
    else:
        out["calibration_frac_peak"] = None
        out["calibration_ok"] = True   # no CPU band; presence = alive
    return out


def run_llama(config: str = "mid"):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import (LlamaForCausalLM, llama_1b, llama_mid,
                                   llama_small, llama_tiny)

    paddle.seed(0)
    if config == "mid":
        # ~0.7B, GQA 3:1; flash attention keeps activations light enough
        # to train without remat at batch 4
        cfg = llama_mid(dtype="bfloat16", use_recompute=False)
        batch, seq, iters = 4, 2048, 10
    elif config == "mid4k":
        # seq-4096 long-context row (BASELINE protocol): chunked CE
        # frees the [B,S,V] logits so b2 s4096 trains without remat
        cfg = llama_mid(dtype="bfloat16", use_recompute=False,
                        chunked_ce_tokens=1024,
                        max_position_embeddings=4096)
        batch, seq, iters = 2, 4096, 10
    elif config == "mid8k":
        # long-context flagship row (VERDICT r3 #6): seq-8192 flash
        # attention on one chip, chunked CE
        cfg = llama_mid(dtype="bfloat16", use_recompute=False,
                        chunked_ce_tokens=1024,
                        max_position_embeddings=8192)
        batch, seq, iters = 1, 8192, 10
    elif config == "1b":
        # largest-fitting row: ~1.0B. r4 recipe (VERDICT r3 #3, the
        # 0.65B->1B MFU cliff): bf16 Adam moments (AdamW
        # moment_dtype='bfloat16' halves optimizer-state HBM) buy back
        # enough memory to drop full remat for full_attn granularity
        # (MLP activations stored, attention rematerialized) — measured
        # 57.9% -> 70.9% MFU at b4 s2048
        cfg = llama_1b(dtype="bfloat16", use_recompute=True,
                       recompute_granularity="full_attn",
                       chunked_ce_tokens=1024)
        batch, seq, iters = 4, 2048, 10
    elif config == "small":
        cfg = llama_small(dtype="bfloat16", use_recompute=False)
        batch, seq, iters = 8, 1024, 10
    else:
        cfg = llama_tiny(dtype="bfloat16")
        batch, seq, iters = 8, 256, 10

    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                          weight_decay=0.01,
                          moment_dtype="bfloat16" if config == "1b"
                          else None)
    step = paddle.jit.TrainStep(model, lambda o, l: model.loss(o, l), opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))

    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    dt = _timed_train_steps(step, ids, ids, iters) * iters
    final = float(step(ids, ids))   # loss AFTER all trained steps
    tokens_per_sec = batch * seq * iters / dt
    n_params = model.num_params()
    mfu = _mfu(tokens_per_sec, n_params, cfg, seq)
    return {
        "metric": f"llama_{config}_train_tokens_per_sec_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "final_loss": round(final, 4),
            "step_ms": round(1000 * dt / iters, 2),
        },
    }


def _mfu(tokens_per_sec, n_params, cfg, seq):
    """PaLM-appendix MFU: flops/token = 6N + 12*L*H*Q*S — ONE formula
    for every bench row (llama and MoE) so the numbers stay
    comparable. For MoE pass the ACTIVATED parameter count."""
    l_, h_, q_ = (cfg.num_hidden_layers, cfg.num_attention_heads,
                  cfg.hidden_size // cfg.num_attention_heads)
    fpt = 6 * n_params + 12 * l_ * h_ * q_ * seq
    return tokens_per_sec * fpt / detect_peak_flops()


def _timed_train_steps(step, inputs, labels, iters):
    """Per-step wall seconds of a TrainStep via dispatch-count
    differencing (cancels the ~75 ms tunnel fetch RTT that polluted the
    r2/r3 numbers — see paddle_tpu.utils.timing)."""
    from paddle_tpu.utils.timing import timed_dispatch_diff
    return timed_dispatch_diff(lambda a, b: step(a, b)._value,
                               (inputs, labels), calls=(2, 2 + iters),
                               repeats=2)


def _run_moe_config(mode, num_experts=8, moe_intermediate=1408,
                    hidden=1024, intermediate=2816, tag=None,
                    moment_dtype=None):
    """One MoE-LM training measurement; returns rows keyed by tag."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.moe_lm import MoEConfig, MoEForCausalLM

    out = {}
    tag = tag or f"moe_{mode}"
    batch, seq, iters = 4, 2048, 8
    paddle.seed(0)
    cfg = MoEConfig(dtype="bfloat16", hidden_size=hidden,
                    intermediate_size=intermediate,
                    moe_intermediate_size=moe_intermediate,
                    num_hidden_layers=8, num_attention_heads=16,
                    num_key_value_heads=8, num_experts=num_experts,
                    num_experts_per_tok=2,
                    max_position_embeddings=2048,
                    chunked_ce_tokens=1024,
                    moe_dispatch_mode=mode)
    model = MoEForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          weight_decay=0.01, moment_dtype=moment_dtype)
    step = paddle.jit.TrainStep(model, lambda o, l: model.loss(o, l),
                                opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, ids)
    float(loss)
    tok = batch * seq / _timed_train_steps(step, ids, ids, iters)
    out[f"{tag}_tok_per_sec"] = round(tok, 1)
    out[f"{tag}_mfu_activated"] = round(
        _mfu(tok, model.num_activated_params(), cfg, seq), 4)
    out[f"{tag}_total_params"] = model.num_params()
    out[f"{tag}_activated_params"] = model.num_activated_params()
    return out


def _moe_phase_breakdown():
    """route/permute/expert-mm/combine wall split of ONE ragged MoE FFN
    at the bench geometry (VERDICT r4 #3: say where the non-MXU time
    goes). Forward only, each phase a jitted scanned program with the
    dispatch-diff timer; TPU only (the grouped matmuls are sized for
    the MXU)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.moe import _grouped_mm

    t_, d_, h_, e_, k_ = 8192, 1024, 1408, 8, 2
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randn(t_, d_).astype(np.float32)) \
        .astype(jnp.bfloat16)
    gate_w = jnp.asarray(rng.randn(d_, e_).astype(np.float32) * 0.02)
    w1 = jnp.asarray(rng.randn(e_, d_, h_).astype(np.float32) * 0.02) \
        .astype(jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(e_, h_, d_).astype(np.float32) * 0.02) \
        .astype(jnp.bfloat16)

    def route_of(tok):
        logits = tok.astype(jnp.float32) @ gate_w
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top_i = jax.lax.top_k(probs, k_)
        return top_i.astype(jnp.int32), top_p

    top_i, top_p = jax.jit(route_of)(tokens)
    flat_expert = top_i.reshape(-1)
    order = jnp.argsort(flat_expert, stable=True).astype(jnp.int32)
    group_sizes = jnp.bincount(flat_expert, length=e_).astype(jnp.int32)
    xs = jnp.take(tokens, order // k_, axis=0)
    gates = top_p / top_p.sum(-1, keepdims=True)
    ys = jax.jit(lambda a, g: _grouped_mm(a, w2, g))(
        jax.jit(lambda a, g: jax.nn.gelu(_grouped_mm(a, w1, g)))(
            xs, group_sizes), group_sizes)

    # every phase folds the scan carry into its input so the body can't
    # be hoisted; scalar checksum return (tunnel fetch stays tiny)
    def ph_route(tok, c):
        ti, tp = route_of(tok + (c * 1e-24).astype(tok.dtype))
        return jnp.float32(jnp.sum(ti) + jnp.sum(tp))

    def ph_permute(fe, tok, c):
        fe2 = fe + (c * 1e-24).astype(jnp.int32)
        o = jnp.argsort(fe2, stable=True).astype(jnp.int32)
        gs = jnp.bincount(fe2, length=e_)
        x2 = jnp.take(tok, o // k_, axis=0)
        return (jnp.sum(o).astype(jnp.float32) + jnp.sum(gs)
                + jnp.sum(x2.astype(jnp.float32)))

    def ph_mm(x2, gs, c):
        hh = jax.nn.gelu(_grouped_mm(x2 + (c * 1e-24).astype(x2.dtype),
                                     w1, gs))
        yy = _grouped_mm(hh, w2, gs)
        return jnp.sum(yy.astype(jnp.float32))

    def ph_combine(yy, o, g, c):
        y2 = yy + (c * 1e-24).astype(yy.dtype)
        ws = g.reshape(t_ * k_)[o].astype(y2.dtype)
        outv = jnp.zeros((t_, d_), y2.dtype).at[o // k_].add(
            y2 * ws[:, None])
        return jnp.sum(outv.astype(jnp.float32))

    def timed(fn, *args):
        def make(iters):
            def many(*a):
                def body(c, _):
                    return fn(*a, c), None
                y, _ = jax.lax.scan(body, jnp.float32(0), None,
                                    length=iters)
                return y
            return jax.jit(many)
        return round(_timed_scan_diff(make, 16, *args) * 1e3, 3)

    return {
        "moe_phase_route_ms": timed(ph_route, tokens),
        "moe_phase_permute_ms": timed(ph_permute, flat_expert, tokens),
        "moe_phase_expert_mm_ms": timed(ph_mm, xs, group_sizes),
        "moe_phase_combine_ms": timed(ph_combine, ys, order, gates),
    }


def run_moe():
    """MoE-LM training rows (VERDICT r3 #7 / r4 #3): dense (GShard
    one-hot) vs ragged (sort-based dropless, Pallas grouped matmul) at
    E=8 top-2, a DeepSeek-class E=64 ragged row, and the ragged phase
    breakdown. MFU is over ACTIVATED params (the MoE convention)."""
    import jax

    out = _run_moe_config("dense")
    out.update(_run_moe_config("ragged"))
    # DeepSeek-class expert count: E=64 top-2, narrower experts so the
    # optimizer state still fits one chip (H=512 keeps 4 MXU tiles)
    out.update(_run_moe_config("ragged", num_experts=64,
                               moe_intermediate=512,
                               tag="moe_ragged_e64"))
    # MXU-efficient width (VERDICT r4 #3 resolution): at hidden 2048
    # (the llama_mid width) the same ragged machinery reaches 58.5%
    # activated MFU — the r4 41% was width-starvation of the whole
    # model, not dispatch cost. bf16 Adam moments keep the 815M-param
    # optimizer state on-chip.
    out.update(_run_moe_config("ragged", hidden=2048,
                               moe_intermediate=2048, intermediate=4096,
                               moment_dtype="bfloat16",
                               tag="moe_ragged_wide"))
    # back-compat aliases for the r3/r4 row names
    out["moe_total_params"] = out["moe_ragged_total_params"]
    out["moe_activated_params"] = out["moe_ragged_activated_params"]
    # Where the time goes (measured r5, per-step xprof attribution at
    # the h1024 geometry, 132.5 ms/step): ragged expert matmuls 30.2 ms
    # (XLA's native ragged_dot, ~75 TF/s f+b), flash attention
    # fwd+bwd 25.7 ms, dense/CE dot_generals ~28 ms, dispatch/combine
    # scatter-adds 12.3 ms, AdamW update 7.5 ms, rest copies/host. The
    # dense-dispatch row at the SAME width scores 34% vs ragged's 41%,
    # so the gap vs the 74%-MFU llama rows is the narrow model (every
    # piece runs at 40-60% at h1024), not the MoE machinery — hence
    # the moe_ragged_wide row, where ragged hits >=55% (ask target).
    out["moe_account"] = ("h1024 step 132.5ms: ragged_dot 30.2, flash "
                          "attn 25.7, dense+CE dots 28, scatter 12.3, "
                          "adamw 7.5; width-bound, see moe_ragged_wide")
    if jax.default_backend() == "tpu":
        out.update(_moe_phase_breakdown())
    return out


def run_resnet():
    """ResNet-50 training imgs/sec/chip (BASELINE.md secondary metric)."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.vision.models import resnet50
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    for p in model.parameters():  # bf16 weights, f32 masters in SGD
        p._replace(p._value.astype("bfloat16"))
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda o, l: F.cross_entropy(o.astype("float32"), l), opt)

    batch, iters = 256, 10
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(
        rng.randn(batch, 3, 224, 224).astype(np.float32)).astype("bfloat16")
    y = paddle.to_tensor(rng.randint(0, 1000, batch).astype(np.int64))
    for _ in range(2):
        loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    float(loss)
    dt = time.perf_counter() - t0
    return {"resnet50_imgs_per_sec": round(batch * iters / dt, 1),
            "resnet50_step_ms": round(1000 * dt / iters, 2)}


def run_dit():
    """DiT-XL/2 diffusion-transformer training row (BASELINE.md configs:
    SD3/DiT class). 256px-latent setup: [B, 4, 32, 32] noisy latents,
    class conditioning, MSE to the noise target. MFU uses the PaLM
    formula over the 256-token patch sequence."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.dit import DiT, dit_xl_2

    paddle.seed(0)
    cfg = dit_xl_2(dtype="bfloat16", learn_sigma=False)
    batch, iters = 32, 8
    model = DiT(cfg)
    opt = optimizer.AdamW(parameters=model.parameters(),
                          learning_rate=1e-4)

    def loss_fn(out, target):
        import paddle_tpu.nn.functional as F
        return F.mse_loss(out, target)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(
        rng.randn(batch, 4, 32, 32).astype(np.float32)).astype("bfloat16")
    t = paddle.to_tensor(rng.randint(0, 1000, batch).astype(np.int32))
    y = paddle.to_tensor(
        rng.randint(0, cfg.num_classes, batch).astype(np.int32))
    noise = paddle.to_tensor(
        rng.randn(batch, 4, 32, 32).astype(np.float32)).astype("bfloat16")
    for _ in range(2):
        loss = step((x, t, y), noise)
    float(loss)
    dt = _timed_train_steps(step, (x, t, y), noise, iters) * iters
    n_params = model.num_params()
    n_tokens = (cfg.input_size // cfg.patch_size) ** 2
    imgs_per_sec = batch * iters / dt
    flops_per_img = 6 * n_params * n_tokens + \
        12 * cfg.depth * cfg.hidden_size * n_tokens ** 2
    mfu = imgs_per_sec * flops_per_img / detect_peak_flops()
    return {"dit_xl2_imgs_per_sec": round(imgs_per_sec, 1),
            "dit_xl2_mfu": round(mfu, 4),
            "dit_xl2_params": n_params,
            "dit_xl2_step_ms": round(1000 * dt / iters, 2)}


def run_decode():
    """Paged-KV serving decode tokens/sec (Pallas decode kernel).

    Methodology (changed r4): the decode phase is timed at TWO scan
    lengths and differenced — a blocking token fetch through the axon
    tunnel costs a ~75 ms (±several ms) round trip, which the r2/r3
    numbers divided into ~63 steps (~1.2 ms/step of constant noise;
    the r3 '-7%' decode regression sat entirely inside that band).
    The differenced number is pure device time per step."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference.paged_decode import PagedLlamaDecoder

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    batch, prompt, block_size = 8, 512, 64
    steps_lo, steps_hi = 64, 192
    dec = PagedLlamaDecoder(
        model,
        num_blocks=(prompt + steps_hi + block_size) * batch // block_size
        + batch, block_size=block_size)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    # warmup BOTH lengths (the scanned decode loop's length is a
    # compile-time constant), then take best-of-2 per length
    dt = {}
    for steps in (steps_lo, steps_hi):
        dec.generate(ids, max_new_tokens=steps)
        best = float("inf")
        for _ in range(2):
            timings = {}
            out = dec.generate(ids, max_new_tokens=steps,
                               timings=timings)
            best = min(best, timings["decode_s"])
        assert out.shape == (batch, prompt + steps)
        dt[steps] = best
    per_step = (dt[steps_hi] - dt[steps_lo]) / (steps_hi - steps_lo)
    raw = dt[steps_lo] / (steps_lo - 1)     # r2/r3-comparable (RTT in)
    out = {"paged_decode_tok_per_sec": round(batch / per_step, 1),
           "paged_decode_batch": batch,
           "paged_decode_ms_per_step": round(1000 * per_step, 2),
           "paged_decode_ms_per_step_with_rtt": round(1000 * raw, 2),
           "prefill_ms": round(1000 * timings["prefill_s"], 2)}
    # weight-only int4 decode (nibble-packed, VERDICT bandwidth story:
    # decode is weight-HBM-bound, so 4x smaller reads)
    del dec
    import gc
    gc.collect()
    dec4 = PagedLlamaDecoder(
        model,
        num_blocks=(prompt + steps_hi + block_size) * batch // block_size
        + batch, block_size=block_size, weight_dtype="int4")
    dt4 = {}
    for steps in (steps_lo, steps_hi):
        dec4.generate(ids, max_new_tokens=steps)
        best = float("inf")
        for _ in range(2):
            timings = {}
            dec4.generate(ids, max_new_tokens=steps, timings=timings)
            best = min(best, timings["decode_s"])
        dt4[steps] = best
    per4 = (dt4[steps_hi] - dt4[steps_lo]) / (steps_hi - steps_lo)
    out["paged_decode_int4_tok_per_sec"] = round(batch / per4, 1)
    out["paged_decode_int4_ms_per_step"] = round(1000 * per4, 2)
    return out


def run_profile():
    """Hardware-proven device profiler row (VERDICT r4 #6): drive
    profiler.Profiler (which starts jax.profiler's xprof capture) over
    three real training steps on the chip, then assert the artifact
    contains DEVICE-lane kernel events — the TPU analog of the
    reference's CudaTracer timeline (/root/reference/paddle/fluid/
    platform/profiler/cuda_tracer.h). Ships the trace path so the
    capture is inspectable after the run."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer, profiler
    from paddle_tpu.models import LlamaForCausalLM, llama_small

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = paddle.jit.TrainStep(model, lambda o, l: model.loss(o, l),
                                opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(4, 1024)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    prof = profiler.Profiler(
        targets=[profiler.ProfilerTarget.CPU, profiler.ProfilerTarget.TPU])
    prof.start()
    for _ in range(3):
        loss = step(ids, ids)
    float(loss)
    prof.stop()
    trace_dir = prof.device_trace_dir
    summary = profiler.device_trace_summary(trace_dir) if trace_dir \
        else {"device_lanes": [], "device_events": 0, "top_kernels": []}
    assert summary["device_events"] > 0, \
        f"no device events captured in {trace_dir}"
    host_path = f"/tmp/paddle_tpu_profile_host_{os.getpid()}.json"
    prof.export(host_path)
    return {
        "profile_trace_dir": trace_dir,
        "profile_device_lanes": summary["device_lanes"],
        "profile_device_events": summary["device_events"],
        "profile_top_kernels": summary["top_kernels"][:3],
        "profile_host_chrome_json": host_path,
    }


def run_8b():
    """Llama-3-8B serving on ONE 16 GB chip (VERDICT r4 #2 — the
    BASELINE.md north-star model class, finally at its real geometry):
    bf16 weights (~16 GB) cannot fit, so the decoder is built lazily
    with on-device quantization (int4 ~3.9 GB, int8 ~7.5 GB) via
    PagedLlamaDecoder.from_config; the KV pool (bf16) is sized to the
    remaining HBM. Rows: raw paged decode tok/s at both widths
    (dispatch-diff timed like the 0.5B row) + an int4 serving-capacity
    drain through the full engine."""
    import gc
    import paddle_tpu as paddle
    from paddle_tpu.models import llama_3_8b
    from paddle_tpu.inference.paged_decode import PagedLlamaDecoder
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_3_8b(dtype="bfloat16")
    batch, prompt, block_size = 8, 512, 64
    steps_lo, steps_hi = 32, 96
    num_blocks = (prompt + steps_hi + block_size) * batch // block_size \
        + batch
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    out = {}
    for wd in ("int4", "int8"):
        dec = PagedLlamaDecoder.from_config(
            cfg, weight_dtype=wd, num_blocks=num_blocks,
            block_size=block_size)
        dt = {}
        for steps in (steps_lo, steps_hi):
            dec.generate(ids, max_new_tokens=steps)     # compile warmup
            best = float("inf")
            for _ in range(2):
                timings = {}
                o = dec.generate(ids, max_new_tokens=steps,
                                 timings=timings)
                best = min(best, timings["decode_s"])
            assert o.shape == (batch, prompt + steps)
            dt[steps] = best
        per = (dt[steps_hi] - dt[steps_lo]) / (steps_hi - steps_lo)
        out[f"paged_decode_8b_{wd}_tok_per_sec"] = round(batch / per, 1)
        out[f"paged_decode_8b_{wd}_ms_per_step"] = round(1000 * per, 2)
        out[f"paged_decode_8b_{wd}_prefill_ms"] = round(
            1000 * timings["prefill_s"], 2)
        if wd == "int4":
            # capacity drain through the full engine on the SAME
            # decoder/pool (closed loop, decode-heavy — comparable to
            # the raw decode row above)
            eng = ServingEngine(dec, max_batch_size=batch,
                                prompt_buckets=(128,),
                                chunk_schedule=(16, 64))
            eng.warmup()
            t0 = time.perf_counter()
            for _ in range(batch * 2):
                eng.add_request(rng.randint(0, cfg.vocab_size, 100),
                                SamplingParams(max_new_tokens=128))
            eng.run_to_completion()
            wall = time.perf_counter() - t0
            st = eng.stats()
            decode_s = max(st["time_decode_stall_s"], 1e-9)
            out["serving_8b_int4_capacity_tok_per_sec"] = round(
                st["generated_tokens"] / wall, 1)
            out["serving_8b_int4_capacity_decode_tok_per_sec"] = round(
                st["generated_tokens"] / decode_s, 1)
            out["serving_8b_int4_capacity_wall_s"] = round(wall, 2)
            del eng
        del dec
        gc.collect()
    out["8b_params_total"] = 8.03e9
    return out


def run_serving(weight_dtype=None, concurrency=8):
    """Continuous-batching serving bench (r4 protocol, VERDICT r3 #5):
    OPEN-LOOP Poisson arrivals over mixed prompt buckets (128/256/512)
    and mixed max_new_tokens (32..96), so p50/p99 are non-degenerate
    and the engine schedules under realistic churn. Reports throughput,
    latency/TTFT percentiles, and the prefill/decode-stall/host wall
    breakdown (where the engine-vs-raw-decode gap goes)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    block_size = 64
    n_requests = concurrency * 3
    eng = ServingEngine(
        model, max_batch_size=concurrency,
        num_blocks=concurrency * ((512 + 96) // block_size + 2) + 8,
        block_size=block_size, prompt_buckets=(128, 256, 512),
        weight_dtype=weight_dtype, chunk_size=16)
    rng = np.random.RandomState(0)
    # compile every variant up front so no request pays a compile
    # (warmup clears its own throwaway stats)
    eng.warmup()

    # Poisson arrivals at ~80% of the drained-throughput estimate the
    # r3 run measured (~600 tok/s / 64 tok ≈ 9 req/s full capacity →
    # 0.8 * 9 = 7.2 req/s): the queue drains between bursts, so the
    # percentiles describe an operating point, not saturation noise
    arrivals = np.cumsum(rng.exponential(1.0 / 7.2, n_requests))
    lens = rng.choice([100, 200, 460], n_requests)
    news = rng.randint(32, 97, n_requests)
    t0 = time.perf_counter()
    sent = 0
    while sent < n_requests or eng.has_work:
        now = time.perf_counter() - t0
        while sent < n_requests and arrivals[sent] <= now:
            eng.add_request(
                rng.randint(0, cfg.vocab_size, int(lens[sent])),
                SamplingParams(max_new_tokens=int(news[sent])))
            sent += 1
        if not eng.step() and sent < n_requests:
            # idle until the next arrival
            time.sleep(max(0.0, arrivals[sent] - (time.perf_counter()
                                                  - t0)))
    dt = time.perf_counter() - t0
    st = eng.stats()
    gen = st["generated_tokens"]
    tag = f"serving_{'int8' if weight_dtype else 'bf16'}_c{concurrency}"
    return {
        # r4 protocol note: NOT comparable to the r2/r3 closed-loop
        # drain numbers — arrivals are rate-limited (open loop), so
        # tok/s reflects an operating point, not peak drain throughput
        f"{tag}_protocol": "open_loop_poisson_0.8cap_mixed",
        f"{tag}_tok_per_sec": round(gen / dt, 1),
        f"{tag}_latency_p50_s": round(st["latency_p50_s"], 3),
        f"{tag}_latency_p99_s": round(st["latency_p99_s"], 3),
        f"{tag}_ttft_p50_s": round(st["ttft_p50_s"], 3),
        f"{tag}_ttft_p99_s": round(st["ttft_p99_s"], 3),
        f"{tag}_itl_p50_s": round(st["itl_p50_s"], 4),
        f"{tag}_itl_p99_s": round(st["itl_p99_s"], 4),
        f"{tag}_queue_wait_p50_s": round(st["queue_wait_p50_s"], 4),
        f"{tag}_decode_utilization": round(st["decode_utilization"], 4),
        f"{tag}_padded_token_waste": st["padded_token_waste"],
        f"{tag}_prefill_s": round(st["time_prefill_s"], 2),
        f"{tag}_decode_stall_s": round(st["time_decode_stall_s"], 2),
        f"{tag}_host_s": round(st["time_host_s"], 2),
        f"{tag}_wall_s": round(dt, 2),
    }


def run_serving_capacity(concurrency=8, weight_dtype=None):
    """Closed-loop CAPACITY row (the engine-vs-raw-decode gap metric,
    VERDICT r3 weak#4 / r4 #4): all requests enqueued at t0,
    decode-heavy load (short prompts, long generations), drained flat
    out. The decode-phase throughput is directly comparable to
    paged_decode_tok_per_sec (same model/batch geometry); the gap is
    scheduling + sampling + first-token plumbing overhead. r5: the
    128-token chunk rung and batched prefill fetch cut the per-chunk
    tunnel RTTs; int8/int4 rows make the weight-bandwidth win visible
    under the full engine, not just raw decode."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    block_size = 64
    new_tokens = 128
    n_requests = concurrency * 2
    eng = ServingEngine(
        model, max_batch_size=concurrency,
        num_blocks=concurrency * ((128 + new_tokens) // block_size + 2)
        + 8, block_size=block_size, prompt_buckets=(128,),
        weight_dtype=weight_dtype, chunk_schedule=(16, 64, 128))
    eng.warmup()
    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    for _ in range(n_requests):
        eng.add_request(rng.randint(0, cfg.vocab_size, 100),
                        SamplingParams(max_new_tokens=new_tokens))
    eng.run_to_completion()
    dt = time.perf_counter() - t0
    st = eng.stats()
    gen = st["generated_tokens"]
    decode_s = max(st["time_decode_stall_s"], 1e-9)
    tag = "serving_capacity" if weight_dtype is None \
        else f"serving_capacity_{weight_dtype}"
    return {
        f"{tag}_tok_per_sec": round(gen / dt, 1),
        f"{tag}_decode_tok_per_sec": round(gen / decode_s, 1),
        f"{tag}_wall_s": round(dt, 2),
        f"{tag}_prefill_s": round(st["time_prefill_s"], 2),
        f"{tag}_decode_s": round(decode_s, 2),
        f"{tag}_host_s": round(st["time_host_s"], 2),
    }


def run_serving_prefix(weight_dtype=None):
    """Automatic prefix caching A/B (the ISSUE-1 acceptance scenario):
    8 requests sharing a 256-token system prompt (distinct 32-token
    user tails), drained closed-loop with the cache ON vs OFF on
    otherwise identical engines. Cache-on splices the shared prefix's
    pages on admission and prefills only each request's suffix, so the
    prefill-seconds ratio directly measures the FLOPs/TTFT the cache
    buys; tests (tests/test_prefix_cache.py) pin token-identity of the
    two configurations, so this row is pure speed."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    block_size = 32
    n_requests, shared_len, tail_len, new_tokens = 8, 256, 32, 32
    rng = np.random.RandomState(0)
    shared = rng.randint(0, cfg.vocab_size, shared_len).astype(np.int32)
    tails = [rng.randint(0, cfg.vocab_size, tail_len).astype(np.int32)
             for _ in range(n_requests)]
    out = {}
    for pc in (False, True):
        eng = ServingEngine(
            model, max_batch_size=n_requests,
            num_blocks=n_requests
            * ((shared_len + tail_len + new_tokens) // block_size + 2)
            + 8, block_size=block_size,
            prompt_buckets=(64, shared_len + tail_len),
            weight_dtype=weight_dtype, chunk_size=16,
            prefix_caching=pc)
        eng.warmup()
        t0 = time.perf_counter()
        for t in tails:
            eng.add_request(np.concatenate([shared, t]),
                            SamplingParams(max_new_tokens=new_tokens))
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        tag = "prefix_on" if pc else "prefix_off"
        out[f"serving_{tag}_prefill_s"] = round(st["time_prefill_s"], 4)
        out[f"serving_{tag}_ttft_p50_s"] = round(st["ttft_p50_s"], 4)
        out[f"serving_{tag}_ttft_p99_s"] = round(st["ttft_p99_s"], 4)
        out[f"serving_{tag}_wall_s"] = round(wall, 3)
        if pc:
            out["serving_prefix_hit_rate"] = round(
                st["prefix_cache_hit_rate"], 4)
            out["serving_prefix_hit_tokens"] = st[
                "prefix_cache_hit_tokens"]
        del eng
    out["serving_prefix_prefill_speedup_x"] = round(
        out["serving_prefix_off_prefill_s"]
        / max(out["serving_prefix_on_prefill_s"], 1e-9), 2)
    out["serving_prefix_ttft_p50_speedup_x"] = round(
        out["serving_prefix_off_ttft_p50_s"]
        / max(out["serving_prefix_on_ttft_p50_s"], 1e-9), 2)
    return out


def run_serving_interleave(weight_dtype=None):
    """Chunked-prefill A/B (the ISSUE-2 acceptance scenario): 6 short
    requests decode steadily; a 1536-token prompt arrives mid-stream.
    Headline: ITL p99 of the ALREADY-RUNNING requests — monolithic
    prefill (chunked off) stalls every running stream for the whole
    1536-token prefill, chunked prefill interleaves 64-token chunks
    with decode chunks so running streams hiccup by at most ~one chunk
    per decode chunk. Token identity of the two configurations is
    pinned by tests/test_chunked_prefill.py AND re-checked here
    (reported as serving_interleave_tokens_identical); the A/B is
    otherwise pure latency/throughput."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    # geometry: a 4-token decode chunk keeps the per-token ITL
    # attribution stall-sensitive (a T-token chunk dilutes a prefill
    # stall by T — this is the latency-SLO operating point, not the
    # throughput one), and the 1536-token prompt costs ~24
    # decode-chunks of 64-token prefill — the regime the chunked
    # scheduler exists for. The pool is sized so the run JUST fits
    # (warmup then skips the width-4 burst at the long bucket, which
    # production never sees at this capacity anyway).
    block_size = 64
    n_short, short_len, short_new = 6, 96, 160
    long_len, long_new = 1536, 32
    rng = np.random.RandomState(0)
    shorts = [rng.randint(0, cfg.vocab_size, short_len).astype(np.int32)
              for _ in range(n_short)]
    longp = rng.randint(0, cfg.vocab_size, long_len).astype(np.int32)
    out = {}
    toks = {}
    n_blocks = (n_short * -(-(short_len + short_new) // block_size)
                + -(-(long_len + long_new) // block_size) + 1)
    for tag, pc in (("off", None), ("on", 64)):
        eng = ServingEngine(
            model, max_batch_size=n_short + 1,
            num_blocks=n_blocks,
            block_size=block_size, prompt_buckets=(128, long_len),
            weight_dtype=weight_dtype, chunk_size=4,
            prefill_chunk=pc)
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.add_request(p,
                                SamplingParams(max_new_tokens=short_new))
                for p in shorts]
        # let the short streams reach steady decode (~1/4 of their
        # budget emitted) before the long prompt lands
        while eng.generated_tokens < n_short * short_new // 4:
            eng.step()
        rl = eng.add_request(longp,
                             SamplingParams(max_new_tokens=long_new))
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[tag] = [eng.result(r).tolist() for r in rids + [rl]]
        itls = [x for r in rids for x in eng.request(r).itls]
        p = lambda q: float(np.quantile(itls, q))
        out[f"serving_interleave_{tag}_itl_p50_s"] = round(p(0.50), 4)
        out[f"serving_interleave_{tag}_itl_p99_s"] = round(p(0.99), 4)
        out[f"serving_interleave_{tag}_itl_max_s"] = round(max(itls), 4)
        out[f"serving_interleave_{tag}_long_ttft_s"] = round(
            eng.request(rl).ttft_s, 4)
        out[f"serving_interleave_{tag}_tok_per_sec"] = round(
            st["generated_tokens"] / wall, 1)
        out[f"serving_interleave_{tag}_wall_s"] = round(wall, 3)
        if pc:
            out["serving_interleave_decode_utilization"] = round(
                st["decode_utilization"], 4)
            out["serving_interleave_padded_token_waste"] = \
                st["padded_token_waste"]
        del eng
    out["serving_interleave_itl_p99_improvement_x"] = round(
        out["serving_interleave_off_itl_p99_s"]
        / max(out["serving_interleave_on_itl_p99_s"], 1e-9), 2)
    out["serving_interleave_tokens_identical"] = \
        toks["on"] == toks["off"]
    return out


def run_serving_degradation(weight_dtype=None):
    """Fault-tolerance A/B (the ISSUE-4 acceptance scenario): an
    overloaded two-wave burst — more work than the pool/batch can serve
    in the deadline window — with the deadline machinery ON (per-request
    deadline_s + admission shedding + deadline aborts) vs OFF (classic
    best-effort FIFO). Headline: GOODPUT (tokens of requests that
    completed within their deadline, per wall second) and the
    deadline-miss rate. Best-effort serves every request eventually but
    blows the deadline for the tail (work done for a dead-on-arrival
    request is goodput zero); deadlines-on sheds/aborts the infeasible
    tail at admission/step time, so the capacity it saves goes to
    requests that can still make it."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import (EngineOverloaded, ServingEngine,
                                      SamplingParams)

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    block_size = 32
    n_req, plen, new_tokens, max_b = 12, 48, 32, 3
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
               for _ in range(n_req)]

    def mk():
        eng = ServingEngine(
            model, max_batch_size=max_b,
            num_blocks=n_req * ((plen + new_tokens) // block_size + 2)
            + 8, block_size=block_size, prompt_buckets=(plen,),
            weight_dtype=weight_dtype, chunk_size=8)
        eng.warmup(plen)
        return eng

    # calibrate: time one request end-to-end to size a deadline that
    # roughly HALF the burst can meet (the interesting operating point
    # — the overload is relative to measured machine speed, so the row
    # works on any chip/host)
    eng = mk()
    t0 = time.perf_counter()
    eng.add_request(prompts[0], SamplingParams(max_new_tokens=new_tokens))
    eng.run_to_completion()
    per_req_s = time.perf_counter() - t0
    deadline = per_req_s * (n_req / 2) / max_b
    del eng

    out = {"serving_degradation_deadline_s": round(deadline, 3)}
    for tag, use_deadline in (("off", False), ("on", True)):
        eng = mk()
        shed = 0
        rids = []
        t0 = time.perf_counter()

        def submit(wave):
            nonlocal shed
            for p in wave:
                sp = SamplingParams(
                    max_new_tokens=new_tokens,
                    deadline_s=deadline if use_deadline else None)
                try:
                    rids.append(eng.add_request(p, sp))
                except EngineOverloaded:
                    shed += 1

        submit(prompts[: n_req // 2])
        # second wave lands mid-run: by then the engine has a measured
        # token rate, so deadline admission math can actually shed
        # (has_work guard: with deadlines on, wave 1 may abort out
        # entirely before reaching the token threshold)
        while eng.has_work and \
                eng.generated_tokens < n_req // 4 * new_tokens:
            eng.step()
        submit(prompts[n_req // 2:])
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        good_tokens = 0
        misses = shed
        for rid in rids:
            req = eng.request(rid)
            lat = req.latency_s
            if req.state == "done" and lat is not None \
                    and lat <= deadline:
                good_tokens += len(req.out_tokens)
            else:
                misses += 1
        out[f"serving_degradation_{tag}_goodput_tok_per_s"] = round(
            good_tokens / wall, 1)
        out[f"serving_degradation_{tag}_miss_rate"] = round(
            misses / n_req, 3)
        out[f"serving_degradation_{tag}_wall_s"] = round(wall, 3)
        if use_deadline:
            out["serving_degradation_on_shed"] = shed
            out["serving_degradation_on_deadline_aborts"] = \
                st["deadline_misses"]
        del eng
    out["serving_degradation_goodput_x"] = round(
        out["serving_degradation_on_goodput_tok_per_s"]
        / max(out["serving_degradation_off_goodput_tok_per_s"], 1e-9),
        2)
    return out


def run_serving_ragged(weight_dtype=None):
    """Ragged unified prefill+decode batching A/B (the ISSUE-5
    acceptance scenario): 6 short streams decode steadily, then a
    512-token prompt lands mid-stream — the mixed regime where the
    dense path pays merge + decode + per-prefill-chunk dispatches every
    step while the ragged path runs ONE device program per step.
    Headline: device dispatches per delivered token, ragged off / on
    (the acceptance bar is >= 2x) at equal-or-better throughput/ITL,
    with greedy outputs token-identical (re-checked here; the
    preemption/fault cases are pinned by tests/test_ragged_batching.py
    and the --ragged chaos gate)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    cfg = llama_small(dtype="bfloat16")
    block_size = 32
    n_short, short_len, short_new = 6, 96, 96
    long_len, long_new = 512, 32
    rng = np.random.RandomState(0)
    shorts = [rng.randint(0, cfg.vocab_size, short_len).astype(np.int32)
              for _ in range(n_short)]
    longp = rng.randint(0, cfg.vocab_size, long_len).astype(np.int32)
    n_blocks = (n_short * -(-(short_len + short_new) // block_size)
                + -(-(long_len + long_new) // block_size) + 2)
    out = {}
    toks = {}
    for tag, ragged in (("off", False), ("on", True)):
        # model rebuilt per leg: the inter-leg barrier below deletes
        # every live device array, a live model's weights included
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        eng = ServingEngine(
            model, max_batch_size=n_short + 1, num_blocks=n_blocks,
            block_size=block_size, prompt_buckets=(128, long_len),
            weight_dtype=weight_dtype, chunk_size=8, prefill_chunk=32,
            ragged=ragged)
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.add_request(p,
                                SamplingParams(max_new_tokens=short_new))
                for p in shorts]
        while eng.generated_tokens < n_short * short_new // 4:
            eng.step()
        rl = eng.add_request(longp,
                             SamplingParams(max_new_tokens=long_new))
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[tag] = [eng.result(r).tolist() for r in rids + [rl]]
        out[f"serving_ragged_{tag}_tok_per_sec"] = round(
            st["generated_tokens"] / wall, 1)
        out[f"serving_ragged_{tag}_itl_p50_s"] = round(
            st["itl_p50_s"], 4)
        out[f"serving_ragged_{tag}_itl_p99_s"] = round(
            st["itl_p99_s"], 4)
        out[f"serving_ragged_{tag}_device_dispatches"] = \
            st["device_dispatches"]
        out[f"serving_ragged_{tag}_dispatch_per_tok"] = round(
            st["device_dispatches"] / max(st["generated_tokens"], 1),
            4)
        out[f"serving_ragged_{tag}_tokens_per_dispatch"] = round(
            st["tokens_per_dispatch"], 2)
        out[f"serving_ragged_{tag}_padded_token_waste"] = \
            st["padded_token_waste"]
        out[f"serving_ragged_{tag}_wall_s"] = round(wall, 3)
        del eng, model
        # HBM barrier between the A/B legs: the off leg's dead engine
        # stays pinned by jit caches until they're cleared (the same
        # BENCH_r04 leak mode _suite_barrier guards between suites)
        _clear_device_memory()
    out["serving_ragged_dispatch_reduction_x"] = round(
        out["serving_ragged_off_dispatch_per_tok"]
        / max(out["serving_ragged_on_dispatch_per_tok"], 1e-9), 2)
    out["serving_ragged_tokens_identical"] = toks["on"] == toks["off"]
    return out


def run_serving_trace():
    """Serving telemetry overhead A/B (ISSUE 12): the ragged-row
    workload (6 steady decode streams + a 512-token prompt landing
    mid-stream) run twice on the SAME engine config — tracer off vs a
    full Tracer (per-request spans, per-dispatch events, metrics
    registry). The pinned-overhead contract: tracing costs < 5% tok/s
    in-row (asserted, not just reported) and tokens are bit-identical
    (tracing never touches scheduling, sampling or the PRNG stream).
    Each leg is measured twice and scored on its best wall (one-box
    CPU walls jitter a few percent; the mechanism under test is a few
    host-side dict appends per step). The traced leg's flight recorder
    is exported as the bench artifact (serving_trace.perfetto.json,
    summarizable via tools/trace_report.py).

    ISSUE 14 re-pins the bar with the program observatory riding the
    traced leg: counter tracks sample every step and CompileWatch
    records every compile. Both legs bound ragged_idle_cap (closing
    the reachable program grid) and run warmup(seal_programs=True) —
    the grid compiles pre-clock and is SEALED, so the measured reps
    must finish with ZERO unexpected recompiles (asserted in-row, the
    runtime FC2xx on the bench workload; sealing after a cold first
    lap is NOT enough — the second lap splices warm prefixes and
    legitimately reaches schedule shapes a cold lap never dispatches,
    which is exactly the class of surprise the grid warmup closes)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams
    from paddle_tpu.utils.telemetry import Tracer

    cfg = llama_small(dtype="bfloat16")
    block_size = 32
    n_short, short_len, short_new = 6, 96, 96
    long_len, long_new = 512, 32
    rng = np.random.RandomState(0)
    shorts = [rng.randint(0, cfg.vocab_size, short_len).astype(np.int32)
              for _ in range(n_short)]
    longp = rng.randint(0, cfg.vocab_size, long_len).astype(np.int32)
    n_blocks = (n_short * -(-(short_len + short_new) // block_size)
                + -(-(long_len + long_new) // block_size) + 2)
    out = {}
    toks = {}
    tracer = None
    for tag in ("off", "on"):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        tracer = Tracer() if tag == "on" else None
        eng = ServingEngine(
            model, max_batch_size=n_short + 1, num_blocks=n_blocks,
            block_size=block_size, prompt_buckets=(128, long_len),
            chunk_size=8, prefill_chunk=32, ragged=True,
            ragged_idle_cap=32, tracer=tracer)
        eng.warmup(seal_programs=True)
        best = None
        for _rep in range(2):
            eng.clear_finished()
            t0 = time.perf_counter()
            rids = [eng.add_request(
                p, SamplingParams(max_new_tokens=short_new))
                for p in shorts]
            while eng.generated_tokens < n_short * short_new // 4:
                eng.step()
            rl = eng.add_request(
                longp, SamplingParams(max_new_tokens=long_new))
            eng.run_to_completion()
            wall = time.perf_counter() - t0
            gen = eng.stats()["generated_tokens"]
            leg = {"wall": wall, "rate": gen / wall,
                   "toks": [eng.result(r).tolist()
                            for r in rids + [rl]]}
            if best is None or leg["rate"] > best["rate"]:
                best = leg
        if tag == "on":
            # the watch's ledger is cumulative (clear_finished resets
            # only the per-workload engine counters), so this covers
            # every post-seal dispatch across both measured reps
            out["serving_trace_program_compiles"] = \
                eng.compile_watch.compiles
            out["serving_trace_unexpected_recompiles"] = \
                eng.compile_watch.unexpected_recompiles
            out["serving_trace_counter_samples"] = sum(
                1 for r in tracer.records() if r["kind"] == "counter")
            assert eng.compile_watch.unexpected_recompiles == 0, \
                ("measured reps retraced after seal: "
                 f"{eng.compile_watch.unexpected_recompiles} "
                 "unexpected compiles")
            assert out["serving_trace_counter_samples"] > 0, \
                "traced leg sampled no counter tracks"
        toks[tag] = best["toks"]
        out[f"serving_trace_{tag}_tok_per_sec"] = round(best["rate"], 1)
        out[f"serving_trace_{tag}_wall_s"] = round(best["wall"], 3)
        if tracer is not None:
            path = os.path.join(os.path.dirname(
                os.path.abspath(__file__)),
                "serving_trace.perfetto.json")
            tracer.export(path)
            out["serving_trace_artifact"] = path
            out["serving_trace_records"] = tracer.appended
            out["serving_trace_dropped"] = tracer.dropped
        del eng, model
        _clear_device_memory()
    overhead = 1.0 - (out["serving_trace_on_tok_per_sec"]
                      / max(out["serving_trace_off_tok_per_sec"], 1e-9))
    out["serving_trace_overhead_frac"] = round(overhead, 4)
    out["serving_trace_tokens_identical"] = toks["on"] == toks["off"]
    # the acceptance bar, enforced in-row: tracer-off outputs
    # bit-identical, tracer-on within the pinned overhead budget
    assert toks["on"] == toks["off"], \
        "tracing changed serving outputs — it must be schedule-neutral"
    assert overhead < 0.05, \
        f"tracer overhead {overhead:.1%} exceeds the 5% contract"
    return out


def run_serving_kv8():
    """Quantized KV cache A/B (ISSUE 13 acceptance), two legs:

    - ACCURACY (equal pool geometry, llama_tiny): the pinned 6-stream
      greedy workload served on an fp32 pool vs an int8 pool with the
      SAME num_blocks — greedy outputs must be TOKEN-IDENTICAL
      (asserted in-row), with a decoder-level decode-logits rel-error
      probe reported alongside (the dequant path in isolation: one
      prefill + one pool-reading decode step, max |delta| over max
      |logit|). The tiny geometry is the honest pinned workload: its
      512-token vocab keeps untrained-model logit gaps far above the
      quantization noise, while an UNTRAINED llama_small's 32k-vocab
      near-uniform logits flip sub-quantization-step near-ties on
      most streams — real trained models behave like the former (the
      flag's contract tolerates near-tie flips, the identity gate
      needs a workload without them). The bytes-per-token reduction
      is read off the engines' stats (f32 head_dim-32 pool: 3.56x;
      bf16 head_dim-128 serving pools: 1.94x; acceptance >= 1.8x).
    - CAPACITY (equal pool HBM BYTES, tiny bf16 geometry): int8 pages
      are smaller, so the same byte budget holds ~1.8x the BLOCKS —
      the fp32 leg gets N blocks and the int8 leg the block count the
      same bytes buy (equal num_blocks would give bit-identical
      allocator behavior by construction: the quantization win IS
      more pages per byte). An oversubscribed optimistic-admission
      burst then shows the quantized pool running strictly fewer
      OOM-preemptions (asserted; deterministic closed loop) at higher
      peak concurrency — the mechanism that cuts the preemption/
      adapter-refault rates the chaos legs measure."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import ServingEngine, SamplingParams

    out = {}
    # ---- accuracy leg: equal geometry, fp32 vs int8 pool -------------
    cfg = llama_tiny()
    block_size = 16
    n_str, plen, n_new = 6, 64, 64
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
               for _ in range(n_str)]
    n_blocks = n_str * (-(-(plen + n_new) // block_size) + 1) + 2
    toks = {}
    bpt = {}
    for tag, kvq in (("fp32", None), ("int8", "int8")):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        eng = ServingEngine(
            model, max_batch_size=n_str, num_blocks=n_blocks,
            block_size=block_size, prompt_buckets=(plen,),
            chunk_size=8, prefill_chunk=32, ragged=True,
            kv_quant=kvq)
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.add_request(p,
                                SamplingParams(max_new_tokens=n_new))
                for p in prompts]
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[tag] = [eng.result(r).tolist() for r in rids]
        bpt[tag] = st["kv_bytes_per_token"]
        pre = f"serving_kv8_{tag}"
        out[f"{pre}_tok_per_sec"] = round(
            st["generated_tokens"] / wall, 1)
        out[f"{pre}_itl_p50_s"] = round(st["itl_p50_s"], 4)
        out[f"{pre}_kv_pool_bytes"] = st["kv_pool_bytes"]
        out[f"{pre}_kv_bytes_per_token"] = round(
            st["kv_bytes_per_token"], 1)
        out[f"{pre}_wall_s"] = round(wall, 3)
        if tag == "int8":
            # decode-logits rel-error probe on the SAME model: one
            # prefill + one decode step per pool mode, the dequant
            # path in isolation (reported, not gated — the token
            # identity below is the accuracy contract)
            out["serving_kv8_logits_rel_err"] = round(
                _kv8_logits_probe(model, block_size), 6)
        del eng, model
        _clear_device_memory()
    out["serving_kv8_tokens_identical"] = toks["int8"] == toks["fp32"]
    out["serving_kv8_bytes_per_token_reduction_x"] = round(
        bpt["fp32"] / max(bpt["int8"], 1e-9), 2)
    assert out["serving_kv8_tokens_identical"], \
        "int8 KV pool changed greedy outputs on the pinned workload"
    assert out["serving_kv8_bytes_per_token_reduction_x"] >= 1.8, \
        (f"KV bytes/token reduction "
         f"{out['serving_kv8_bytes_per_token_reduction_x']}x below "
         f"the 1.8x acceptance bar")

    # ---- capacity leg: equal pool HBM bytes, oversubscribed ----------
    tcfg = llama_tiny()
    tl, thd = tcfg.num_hidden_layers, \
        tcfg.hidden_size // tcfg.num_attention_heads
    tkvh, tbs = tcfg.num_key_value_heads, 8
    # per-block bytes from the ACTUAL plane layouts (this model's
    # pool is f32; the int8 block adds 4 scale bytes per value row):
    # the int8 leg gets exactly the block count the fp32 leg's bytes
    # buy, so the two pools occupy the same HBM
    fp_block_bytes = tl * 2 * tkvh * tbs * thd * 4          # f32 pool
    q_block_bytes = tl * 2 * tkvh * tbs * (thd + 4)         # int8+scale
    cap_blocks = {"fp32": 20,
                  "int8": 20 * fp_block_bytes // q_block_bytes}
    cn, cplen, cnew = 12, 16, 48
    cprompts = [rng.randint(0, tcfg.vocab_size, cplen)
                .astype(np.int32) for _ in range(cn)]
    for tag, kvq in (("fp32", None), ("int8", "int8")):
        paddle.seed(0)
        tmodel = LlamaForCausalLM(tcfg)
        tmodel.eval()
        eng = ServingEngine(
            tmodel, max_batch_size=6, num_blocks=cap_blocks[tag],
            block_size=tbs, prompt_buckets=(16, 32), chunk_size=4,
            prefill_chunk=8, ragged=True, admission="optimistic",
            kv_quant=kvq)
        # the equal-bytes math must match the REAL plane layouts, or
        # the A/B silently stops being an equal-HBM comparison
        want = cap_blocks[tag] * (fp_block_bytes if kvq is None
                                  else q_block_bytes)
        assert eng.stats()["kv_pool_bytes"] == want, \
            (tag, eng.stats()["kv_pool_bytes"], want)
        eng.warmup()
        for p in cprompts:
            eng.add_request(p, SamplingParams(max_new_tokens=cnew))
        peak = 0
        t0 = time.perf_counter()
        while eng.step():
            peak = max(peak, sum(1 for r in eng._slots
                                 if r is not None))
        wall = time.perf_counter() - t0
        st = eng.stats()
        pre = f"serving_kv8_cap_{tag}"
        out[f"{pre}_num_blocks"] = cap_blocks[tag]
        out[f"{pre}_oom_preemptions"] = st["preemptions"]
        out[f"{pre}_recompute_tokens"] = st["recompute_tokens"]
        out[f"{pre}_peak_concurrency"] = peak
        out[f"{pre}_finished"] = st["finished"]
        out[f"{pre}_wall_s"] = round(wall, 3)
        del eng, tmodel
        _clear_device_memory()
    out["serving_kv8_cap_equal_bytes"] = (
        cap_blocks["fp32"] * fp_block_bytes)
    assert out["serving_kv8_cap_int8_oom_preemptions"] \
        < out["serving_kv8_cap_fp32_oom_preemptions"], \
        ("the quantized pool must preempt strictly less than the fp32 "
         "pool at equal HBM bytes "
         f"({out['serving_kv8_cap_int8_oom_preemptions']} vs "
         f"{out['serving_kv8_cap_fp32_oom_preemptions']})")
    return out


def _kv8_logits_probe(model, block_size):
    """Max relative decode-logits error of the int8 pool vs the fp32
    pool on one pinned prompt: one bucketed prefill (writes the pool)
    plus one decode step (READS it back — dense-prefill logits alone
    would show zero error: the chunk attends its own fresh K/V)."""
    import jax.numpy as jnp
    from paddle_tpu.inference.paged_decode import PagedLlamaDecoder
    rng = np.random.RandomState(7)
    plen = 64
    prompt = rng.randint(0, model.cfg.vocab_size, plen).astype(np.int32)
    outs = {}
    for tag, kvq in (("fp", None), ("q", "int8")):
        dec = PagedLlamaDecoder(model, num_blocks=8,
                                block_size=block_size, kv_quant=kvq)
        cache = dec.cache
        cache.allocate(0, plen + 2)
        slots = np.asarray([[cache.extend(0) for _ in range(plen)]],
                           np.int32)
        logits, cache.k, cache.v = dec._prefill(
            dec.weights, cache.k, cache.v,
            jnp.asarray(prompt[None]), jnp.asarray(slots))
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        slot = cache.extend(0)
        tbl = np.asarray([cache.block_table(0, dec.max_pages)],
                         np.int32)
        dl, _, _ = dec._decode_logits(
            dec.weights, cache.k, cache.v, tok, jnp.asarray(tbl),
            jnp.asarray([plen], jnp.int32),
            jnp.asarray([slot], jnp.int32))
        outs[tag] = np.asarray(dl, np.float32)[0]
        del dec, cache
    return float(np.max(np.abs(outs["q"] - outs["fp"]))
                 / max(float(np.max(np.abs(outs["fp"]))), 1e-9))


def run_serving_msteps():
    """Multi-step fused decode A/B (ISSUE 16 acceptance): the pinned
    6-stream greedy workload served with multi_step=1 vs multi_step=4
    on otherwise-identical ragged engines. One fused window runs
    k * chunk_size decode iterations inside ONE device program
    (lax.scan with in-program KV append, EOS bookkeeping and sampling
    carried across iterations), so the k=4 leg must deliver >= 3x
    fewer device dispatches per delivered token (asserted) at
    equal-or-better tok/s, with greedy outputs TOKEN-IDENTICAL
    (asserted in-row). Both legs run with profile_every=1 so every
    dispatch feeds the sampled attribution histograms; the
    host_schedule + dispatch_queue attribution — the ITL floor PR
    14's observatory measured — is reported PER DELIVERED TOKEN and
    must shrink on the fused leg (each fused window pays the
    host-schedule + dispatch-queue floor once for k * chunk_size
    tokens instead of once per chunk; measured ~2x on CPU)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import ServingEngine, SamplingParams

    cfg = llama_tiny()
    n_str, plen, n_new = 6, 16, 128
    block_size = 16
    n_blocks = n_str * (-(-(plen + n_new) // block_size) + 1) + 2
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
               for _ in range(n_str)]
    out = {}
    toks = {}
    dpt = {}
    tps = {}
    for k in (1, 4):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        eng = ServingEngine(
            model, max_batch_size=n_str, num_blocks=n_blocks,
            block_size=block_size, prompt_buckets=(plen,),
            chunk_size=4, prefill_chunk=plen, ragged=True,
            multi_step=k, profile_every=1)
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.add_request(p,
                                SamplingParams(max_new_tokens=n_new))
                for p in prompts]
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[k] = [eng.result(r).tolist() for r in rids]
        dpt[k] = st["device_dispatches"] / max(st["generated_tokens"],
                                               1)
        tps[k] = st["generated_tokens"] / wall
        hg = eng._profile_metrics().snapshot()["histograms"]
        host = hg["profile.host_schedule_s"]["sum"]
        queue = hg["profile.dispatch_queue_s"]["sum"]
        hq_us = 1e6 * (host + queue) / max(st["generated_tokens"], 1)
        pre = f"serving_msteps_k{k}"
        out[f"{pre}_tok_per_sec"] = round(tps[k], 1)
        out[f"{pre}_itl_p50_s"] = round(st["itl_p50_s"], 4)
        out[f"{pre}_itl_p99_s"] = round(st["itl_p99_s"], 4)
        out[f"{pre}_device_dispatches"] = st["device_dispatches"]
        out[f"{pre}_dispatches_per_token"] = round(dpt[k], 4)
        out[f"{pre}_tokens_per_dispatch"] = round(
            st["tokens_per_dispatch"], 2)
        out[f"{pre}_fused_windows"] = st["multi_step_windows"]
        out[f"{pre}_host_overhead_us_per_token"] = round(hq_us, 1)
        out[f"{pre}_wall_s"] = round(wall, 3)
        del eng, model
        _clear_device_memory()
    out["serving_msteps_tokens_identical"] = toks[4] == toks[1]
    out["serving_msteps_dispatch_reduction_x"] = round(
        dpt[1] / max(dpt[4], 1e-9), 2)
    out["serving_msteps_tok_per_sec_ratio"] = round(
        tps[4] / max(tps[1], 1e-9), 3)
    out["serving_msteps_host_overhead_shrink_x"] = round(
        out["serving_msteps_k1_host_overhead_us_per_token"]
        / max(out["serving_msteps_k4_host_overhead_us_per_token"],
              1e-9), 2)
    assert out["serving_msteps_tokens_identical"], \
        "multi_step=4 changed greedy outputs on the pinned workload"
    assert out["serving_msteps_dispatch_reduction_x"] >= 3.0, \
        (f"dispatch reduction "
         f"{out['serving_msteps_dispatch_reduction_x']}x below the 3x "
         f"acceptance bar")
    assert out["serving_msteps_tok_per_sec_ratio"] >= 1.0, \
        (f"fused decode must not cost throughput: k=4 at "
         f"{out['serving_msteps_k4_tok_per_sec']} tok/s vs k=1 at "
         f"{out['serving_msteps_k1_tok_per_sec']}")
    assert out["serving_msteps_host_overhead_shrink_x"] > 1.0, \
        (f"fused windows must amortize the host-schedule/dispatch-"
         f"queue floor per token "
         f"({out['serving_msteps_host_overhead_shrink_x']}x)")
    return out


def run_serving_spec():
    """Speculative decoding A/B (the ISSUE-9 acceptance scenario): 6
    greedy decode streams, spec on vs off, on TWO workload regimes:

    - "rep" (repetitive/templated — high n-gram hit rate): the
      llama_small geometry with TIED embeddings, whose random-init
      greedy decode locks onto a repeated continuation within a few
      tokens — the honest stand-in for templated traffic (an untrained
      model cannot re-walk meaningful text, but the drafter/verify
      machinery sees exactly what a high-hit production stream gives
      it: long accepted prefixes). Headline: >= 1.5x tok/s with the
      acceptance rate reported.
    - "adv" (adversarial low-hit): the same geometry UNTIED — greedy
      output wanders, n-gram lookups mostly miss or mispredict, and
      the row reports what spec COSTS when drafting doesn't pay
      (flushed pipeline + verify rows that get rejected).

    Greedy outputs must be token-identical spec-on vs spec-off in BOTH
    regimes — asserted here in the bench, not just in the test suite
    (serving_spec_tokens_identical gates the row)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import (ServingEngine, SamplingParams,
                                      SpecConfig)

    block_size = 32
    n_short, short_len, short_new = 6, 64, 96
    out = {}
    for regime, tied in (("rep", True), ("adv", False)):
        cfg = llama_small(dtype="bfloat16", tie_word_embeddings=tied)
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, cfg.vocab_size, short_len)
                   .astype(np.int32) for _ in range(n_short)]
        n_blocks = (n_short
                    * -(-(short_len + short_new) // block_size) + 4)
        toks = {}
        for tag, spec in (("off", None),
                          ("on", SpecConfig(draft_len=16))):
            paddle.seed(0)
            model = LlamaForCausalLM(cfg)
            model.eval()
            eng = ServingEngine(
                model, max_batch_size=n_short, num_blocks=n_blocks,
                block_size=block_size, prompt_buckets=(64, 128),
                chunk_size=8, prefill_chunk=64, ragged=True,
                spec_decode=spec)
            eng.warmup()   # compile outside the clock, like every row
            t0 = time.perf_counter()
            rids = [eng.add_request(
                p, SamplingParams(max_new_tokens=short_new))
                for p in prompts]
            eng.run_to_completion()
            wall = time.perf_counter() - t0
            st = eng.stats()
            toks[tag] = [eng.result(r).tolist() for r in rids]
            pre = f"serving_spec_{regime}_{tag}"
            out[f"{pre}_tok_per_sec"] = round(
                st["generated_tokens"] / wall, 1)
            out[f"{pre}_itl_p50_s"] = round(st["itl_p50_s"], 4)
            out[f"{pre}_itl_p99_s"] = round(st["itl_p99_s"], 4)
            out[f"{pre}_tokens_per_dispatch"] = round(
                st["tokens_per_dispatch"], 2)
            out[f"{pre}_wall_s"] = round(wall, 3)
            if spec is not None:
                out[f"{pre}_acceptance_rate"] = round(
                    st["draft_acceptance_rate"], 3)
                out[f"{pre}_drafted"] = st["drafted_tokens"]
                out[f"{pre}_accepted"] = st["accepted_draft_tokens"]
                out[f"{pre}_rollbacks"] = st["spec_rollbacks"]
            del eng, model
            _clear_device_memory()
        out[f"serving_spec_{regime}_tokens_identical"] = \
            toks["on"] == toks["off"]
        out[f"serving_spec_{regime}_speedup_x"] = round(
            out[f"serving_spec_{regime}_on_tok_per_sec"]
            / max(out[f"serving_spec_{regime}_off_tok_per_sec"],
                  1e-9), 2)
        out[f"serving_spec_{regime}_dispatch_reduction_x"] = round(
            out[f"serving_spec_{regime}_on_tokens_per_dispatch"]
            / max(out[f"serving_spec_{regime}_off_tokens_per_dispatch"],
                  1e-9), 2)
    out["serving_spec_tokens_identical"] = (
        out["serving_spec_rep_tokens_identical"]
        and out["serving_spec_adv_tokens_identical"])
    assert out["serving_spec_tokens_identical"], \
        "speculative decoding changed greedy outputs"
    return out


def run_serving_tp():
    """Multi-chip tensor-parallel serving A/B (ISSUE 8 acceptance): the
    same mixed workload — 6 decode streams plus a mid-stream long
    prompt — served at tp=1/2/4 on the 8-CPU-device mesh, fp32 vs int8
    decode collectives. Reports tok/s and ITL per leg, greedy token
    identity vs tp=1 (fp32 legs MUST be identical; the int8 legs
    report agreement — a sub-quantization-step greedy near-tie may
    flip, which is the compression contract), and the per-step
    per-shard comm bytes read off the TRACED step program by the
    comm-audit walker — the same numbers the committed expectations
    pin for the tiny config. On CPU the shard_map legs pay real
    collective overhead on one physical socket; the mechanism (one
    sharded program per step, 1 allreduce per block) is what this row
    tracks — chip-count speedups need chips."""
    try:
        from tools.flightcheck.comm_audit import (audit_jaxpr,
                                                  ensure_devices)
        ensure_devices(8)
    except Exception as e:     # single-chip TPU process etc.
        return {"serving_tp_skipped": f"{type(e).__name__}: {e}"}
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import ServingEngine, SamplingParams

    # tp-friendly tiny-plus geometry: kvh divisible by 4
    cfg = llama_tiny(hidden_size=256, num_attention_heads=8,
                     num_key_value_heads=4, intermediate_size=704,
                     num_hidden_layers=4)
    n_short, short_len, short_new = 6, 48, 32
    long_len, long_new = 96, 16
    rng = np.random.RandomState(0)
    shorts = [rng.randint(0, cfg.vocab_size, short_len).astype(np.int32)
              for _ in range(n_short)]
    longp = rng.randint(0, cfg.vocab_size, long_len).astype(np.int32)
    out = {}
    toks = {}
    for tag, tp, comm in (("tp1", 1, "fp32"),
                          ("tp2", 2, "fp32"), ("tp2_int8", 2, "int8"),
                          ("tp4", 4, "fp32"), ("tp4_int8", 4, "int8")):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        eng = ServingEngine(
            model, max_batch_size=n_short + 1, num_blocks=64,
            block_size=16, prompt_buckets=(64, long_len),
            chunk_size=8, prefill_chunk=32, ragged=True,
            tp=tp, tp_comm=comm)
        # compile outside the clock (like every other serving row):
        # shard_map compile cost differs systematically across legs
        # and would skew exactly the tp/int8 comparison this row is
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.add_request(p,
                                SamplingParams(max_new_tokens=short_new))
                for p in shorts]
        while eng.generated_tokens < n_short * short_new // 4:
            eng.step()
        rl = eng.add_request(longp,
                             SamplingParams(max_new_tokens=long_new))
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[tag] = [eng.result(r).tolist() for r in rids + [rl]]
        out[f"serving_{tag}_tok_per_sec"] = round(
            st["generated_tokens"] / wall, 1)
        out[f"serving_{tag}_itl_p50_s"] = round(st["itl_p50_s"], 4)
        out[f"serving_{tag}_itl_p99_s"] = round(st["itl_p99_s"], 4)
        out[f"serving_{tag}_wall_s"] = round(wall, 3)
        if tp > 1:
            # per-step comm bytes, read off the program the engine
            # actually dispatches (traced, not profiled)
            T, W = eng.chunk, 8
            S = jax.ShapeDtypeStruct
            i32, f32 = jnp.int32, jnp.float32
            args = (eng.dec.weights, eng.dec.cache.k, eng.dec.cache.v,
                    S((T, W), i32), S((W,), i32), S((W,), i32),
                    S((W,), jnp.bool_), S((W,), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), i32), S((T, W), i32),
                    S((T, W), i32), S((T, W), jnp.bool_),
                    S((eng.max_b + 1, eng.dec.max_pages), i32),
                    S((T, W), f32), S((T, 2), jnp.uint32))
            rows = audit_jaxpr(jax.make_jaxpr(eng._ragged_j)(*args))[0]
            out[f"serving_{tag}_comm_bytes_per_step"] = int(
                sum(r["bytes"] * r["count"] for r in rows))
            out[f"serving_{tag}_collectives_per_step"] = int(
                sum(r["count"] for r in rows))
            out[f"serving_{tag}_tokens_identical_vs_tp1"] = \
                toks[tag] == toks["tp1"]
        del eng, model
        _clear_device_memory()
    ok = (out["serving_tp2_tokens_identical_vs_tp1"]
          and out["serving_tp4_tokens_identical_vs_tp1"])
    out["serving_tp_fp32_token_identity"] = ok
    out["serving_tp_int8_comm_bytes_ratio"] = round(
        out["serving_tp2_int8_comm_bytes_per_step"]
        / max(out["serving_tp2_comm_bytes_per_step"], 1), 3)
    return out


def run_serving_lora():
    """Multi-tenant many-LoRA serving A/B (ISSUE 10 acceptance): the
    same 8 greedy decode streams served by a base-only engine vs an
    engine with a 4-adapter registry (streams 0-5 round-robin over the
    adapters, streams 6-7 stay base-model). Reports tok/s and ITL
    p50/p99 per leg, the adapter-cache hit rate and the mixed-tenant
    batching density (lora rows per dispatch), and ASSERTS the ISSUE
    acceptance inside the row: the two base-model streams of the
    mixed-tenant leg must be TOKEN-IDENTICAL to the base-only engine's
    (adapter_id=None traffic rides the unchanged base program), and
    every step of the mixed leg is still one device program
    (tokens_per_dispatch within the base leg's regime). The tiny-plus
    geometry (the serving_tp row's) tracks the MECHANISM and the lora
    overhead ratio — absolute tok/s needs chips."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import (AdapterRegistry, SamplingParams,
                                      ServingEngine)

    cfg = llama_tiny(hidden_size=256, num_attention_heads=8,
                     num_key_value_heads=4, intermediate_size=704,
                     num_hidden_layers=4)
    n_str, plen, n_new, n_adapters = 8, 48, 48, 4
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
               for _ in range(n_str)]
    aids = [f"a{i % n_adapters}" for i in range(n_str - 2)] \
        + [None, None]
    out = {}
    toks = {}
    for tag in ("base", "lora"):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        reg = None
        if tag == "lora":
            reg = AdapterRegistry(rank=8)
            for i in range(n_adapters):
                reg.register_random(f"a{i}", seed=10 + i, scale=0.05)
        eng = ServingEngine(
            model, max_batch_size=n_str, num_blocks=128,
            block_size=16, prompt_buckets=(64,), chunk_size=8,
            prefill_chunk=32, ragged=True, lora=reg)
        eng.warmup()
        # dry run of the SAME mixed workload: the production (T, W)
        # ragged variant — lora twin included — compiles outside the
        # clock (warmup's single-request leg only warms the narrow
        # rungs); the prefix cache is cleared after so the timed run
        # pays real prefills, not splices of the dry run's blocks
        def _submit():
            return [eng.add_request(
                p, SamplingParams(max_new_tokens=n_new,
                                  adapter_id=(aids[i] if tag == "lora"
                                              else None)))
                for i, p in enumerate(prompts)]
        _submit()
        eng.run_to_completion()
        eng.dec.cache.clear_prefix_cache()
        eng.clear_finished()
        t0 = time.perf_counter()
        rids = _submit()
        eng.run_to_completion()
        wall = time.perf_counter() - t0
        st = eng.stats()
        toks[tag] = [eng.result(r).tolist() for r in rids]
        pre = f"serving_lora_{tag}"
        out[f"{pre}_tok_per_sec"] = round(
            st["generated_tokens"] / wall, 1)
        out[f"{pre}_itl_p50_s"] = round(st["itl_p50_s"], 4)
        out[f"{pre}_itl_p99_s"] = round(st["itl_p99_s"], 4)
        out[f"{pre}_tokens_per_dispatch"] = round(
            st["tokens_per_dispatch"], 2)
        out[f"{pre}_wall_s"] = round(wall, 3)
        if tag == "lora":
            hits, misses = (st["adapter_cache_hits"],
                            st["adapter_cache_misses"])
            out["serving_lora_adapter_hit_rate"] = round(
                hits / max(hits + misses, 1), 3)
            out["serving_lora_rows_per_dispatch"] = round(
                st["lora_rows_per_dispatch"], 2)
            # workload constant (not a measurement): the registry size
            # the 6 tenant streams round-robin over
            out["serving_lora_n_adapters"] = n_adapters
        del eng, model
        _clear_device_memory()
    out["serving_lora_base_rows_identical"] = \
        toks["lora"][6:] == toks["base"][6:]
    assert out["serving_lora_base_rows_identical"], \
        "adapter traffic changed base-model streams"
    out["serving_lora_overhead_x"] = round(
        out["serving_lora_base_tok_per_sec"]
        / max(out["serving_lora_lora_tok_per_sec"], 1e-9), 2)
    return out


def run_serving_dp():
    """Fleet serving A/B (ISSUE 11 acceptance): a SHARED-PREFIX mixed
    workload — 16 greedy requests, 4 per each of 4 block-aligned
    64-token system prefixes, arriving in a seeded SHUFFLED order with
    jittered serving-step gaps between arrivals — served three
    ways: one equal-capacity single engine, an R=2 fleet with
    prefix-affinity routing ON, and the same fleet with affinity OFF
    (pure least-loaded). Reports tok/s, fleet ITL p50/p99, the
    prefix-cache hit rate and the router counters per leg, and ASSERTS
    greedy token identity of every fleet leg against the single engine
    (outputs are replica-independent — the cross-replica identity
    contract). The affinity win is the hit-rate delta: affinity keeps a
    prefix group on the replica whose pool already holds its blocks,
    while least-loaded routing splits groups across replicas and
    re-prefills the shared prefix on both. On CPU one process steps
    both replicas serially, so fleet tok/s carries that host tax —
    the mechanism (routing + hit rate), not chip-count scaling, is
    what this row tracks."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import SamplingParams, ServingEngine
    from paddle_tpu.inference.fleet import Router

    cfg = llama_tiny(hidden_size=256, num_attention_heads=8,
                     num_key_value_heads=4, intermediate_size=704,
                     num_hidden_layers=4)
    n_groups, per_group, pre_len, tail_len, n_new = 4, 4, 64, 16, 16
    rng = np.random.RandomState(0)
    prefixes = [rng.randint(0, cfg.vocab_size, pre_len).astype(np.int32)
                for _ in range(n_groups)]
    # SHUFFLED arrival order with jittered spacing (seeded): group
    # membership decorrelates from instantaneous load, which is the
    # traffic shape affinity exists for — least-loaded routing
    # scatters a group across replicas (each pays its own prefix
    # prefill), affinity keeps it where the blocks are
    order = rng.permutation([g for g in range(n_groups)
                             for _ in range(per_group)])
    prompts = [np.concatenate(
        [prefixes[g], rng.randint(0, cfg.vocab_size,
                                  tail_len).astype(np.int32)])
        for g in order]
    gaps = [int(rng.randint(1, 5)) for _ in prompts]
    geom = dict(num_blocks=48, block_size=16, prompt_buckets=(96,),
                chunk_size=8, prefill_chunk=32, ragged=True)
    out = {}
    toks = {}
    for tag in ("single", "dp2_affinity", "dp2_noaffinity"):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        if tag == "single":
            srv = ServingEngine(model, max_batch_size=4,
                                **{**geom, "num_blocks": 96})
            engines = [srv]
        else:
            srv = Router(model, dp=2, max_batch_size=2,
                         affinity=(tag == "dp2_affinity"), **geom)
            engines = [rep.engine for rep in srv.replicas]
        srv.warmup()

        def _run():
            rids = []
            for p, gap in zip(prompts, gaps):
                rids.append(srv.add_request(
                    p, SamplingParams(max_new_tokens=n_new)))
                for _ in range(gap):
                    srv.step()
            srv.run_to_completion()
            return rids
        # dry run compiles the production (T, W) variants outside the
        # clock; prefix caches cleared after so the timed run pays
        # real prefills and the hit rate measures ROUTING, not leftovers
        _run()
        for e in engines:
            e.dec.cache.clear_prefix_cache()
        srv.clear_finished()
        t0 = time.perf_counter()
        rids = _run()
        wall = time.perf_counter() - t0
        toks[tag] = [srv.result(r).tolist() for r in rids]
        pre = f"serving_dp_{tag}"
        if tag == "single":
            st = srv.stats()
            gen, hit = st["generated_tokens"], st["prefix_cache_hit_rate"]
            itl50, itl99 = st["itl_p50_s"], st["itl_p99_s"]
        else:
            st = srv.stats()["fleet"]
            gen, hit = st["generated_tokens"], st["prefix_cache_hit_rate"]
            itl50, itl99 = st["itl_p50_s"], st["itl_p99_s"]
            out[f"{pre}_affinity_hits"] = st["affinity_hits"]
            out[f"{pre}_spills"] = st["spills"]
            out[f"{pre}_affinity_hit_rate"] = round(
                st["affinity_hit_rate"], 3)
        out[f"{pre}_tok_per_sec"] = round(gen / wall, 1)
        out[f"{pre}_itl_p50_s"] = round(itl50, 4)
        out[f"{pre}_itl_p99_s"] = round(itl99, 4)
        out[f"{pre}_prefix_hit_rate"] = round(hit, 3)
        out[f"{pre}_wall_s"] = round(wall, 3)
        del srv, engines
        _clear_device_memory()
    ok = (toks["dp2_affinity"] == toks["single"]
          and toks["dp2_noaffinity"] == toks["single"])
    out["serving_dp_tokens_identical"] = ok
    assert ok, "fleet greedy outputs diverged from the single engine"
    out["serving_dp2_tok_per_sec"] = \
        out["serving_dp_dp2_affinity_tok_per_sec"]
    # the affinity win: cached-prefix coverage routed-to vs scattered
    out["serving_dp_affinity_hit_gain"] = round(
        out["serving_dp_dp2_affinity_prefix_hit_rate"]
        - out["serving_dp_dp2_noaffinity_prefix_hit_rate"], 3)
    return out


def run_serving_proc():
    """Process-per-replica fleet A/B (ISSUE 19 acceptance): the same
    R=2 greedy workload served by an IN-PROCESS fleet and by a
    PROCESS-TRANSPORT fleet (each replica's engine in a spawned worker
    behind the RPC pipe, heartbeats on, journal maintained at every
    collection). Asserts token identity across the three legs (single
    engine, inproc fleet, process fleet — the transport must be
    token-neutral) and bounds the process-transport tok/s tax at 10%
    vs the inproc fleet (the RPC pickle/unpickle + journal cost per
    step). Then SIGKILLs one worker and reports the supervisor's
    respawn wall — death detection (pipe EOF), fresh spawn, model
    rebuild, warmup replay — the fleet's recovery-time metric."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.inference import SamplingParams, ServingEngine
    from paddle_tpu.inference.fleet import Router

    cfg = llama_tiny(hidden_size=256, num_attention_heads=8,
                     num_key_value_heads=4, intermediate_size=704,
                     num_hidden_layers=4)
    n_req, n_new = 12, 16
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, 80).astype(np.int32)
               for _ in range(n_req)]
    gaps = [int(rng.randint(1, 4)) for _ in prompts]
    geom = dict(num_blocks=48, block_size=16, prompt_buckets=(96,),
                chunk_size=8, prefill_chunk=32, ragged=True)
    out = {}
    toks = {}
    tps = {}
    proc_router = None
    for tag in ("single", "inproc", "process"):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        model.eval()
        if tag == "single":
            srv = ServingEngine(model, max_batch_size=4,
                                **{**geom, "num_blocks": 96})
        else:
            srv = Router(model, dp=2, max_batch_size=2,
                         transport=tag, rpc_timeout_s=300.0, **geom)
        srv.warmup()

        def _run():
            rids = []
            for p, gap in zip(prompts, gaps):
                rids.append(srv.add_request(
                    p, SamplingParams(max_new_tokens=n_new)))
                for _ in range(gap):
                    srv.step()
            srv.run_to_completion()
            return rids
        # dry run compiles the production program variants outside the
        # clock on every leg (the process leg's compiles happen inside
        # the workers); both fleet legs then race the SAME warm state
        _run()
        srv.clear_finished()
        t0 = time.perf_counter()
        rids = _run()
        wall = time.perf_counter() - t0
        toks[tag] = [srv.result(r).tolist() for r in rids]
        st = srv.stats() if tag == "single" else srv.stats()["fleet"]
        gen = st["generated_tokens"]
        tps[tag] = gen / wall
        pre = f"serving_proc_{tag}"
        out[f"{pre}_tok_per_sec"] = round(tps[tag], 1)
        out[f"{pre}_itl_p50_s"] = round(st["itl_p50_s"], 4)
        out[f"{pre}_itl_p99_s"] = round(st["itl_p99_s"], 4)
        out[f"{pre}_wall_s"] = round(wall, 3)
        if tag == "process":
            out[f"{pre}_rpc_retries"] = st["rpc_retries"]
            out[f"{pre}_journal_bytes"] = st["journal_bytes"]
            proc_router = srv     # kept alive for the respawn probe
        else:
            if tag == "inproc":
                srv.close()
            del srv
            _clear_device_memory()
    ok = (toks["inproc"] == toks["single"]
          and toks["process"] == toks["single"])
    out["serving_proc_tokens_identical"] = ok
    assert ok, "transport legs diverged from the single engine"
    out["serving_proc_overhead_pct"] = round(
        100.0 * (1.0 - tps["process"] / max(tps["inproc"], 1e-9)), 1)
    assert tps["process"] >= 0.9 * tps["inproc"], \
        (f"process transport cost {out['serving_proc_overhead_pct']}% "
         f"tok/s vs inproc (bound: 10%)")
    # supervisor recovery wall: SIGKILL one worker, then step until the
    # Router has detected the death (pipe EOF), drained the journal and
    # respawned a warmed worker onto probation
    victim = proc_router.replicas[0]
    t0 = time.perf_counter()
    victim.transport.kill_worker()
    while proc_router.stats()["fleet"]["worker_restarts"] < 1:
        proc_router.step()
        assert time.perf_counter() - t0 < 600.0, "respawn never landed"
    out["serving_proc_respawn_wall_s"] = round(
        time.perf_counter() - t0, 3)
    out["serving_proc_worker_exits"] = \
        proc_router.stats()["fleet"]["worker_exits"]
    proc_router.close()
    del proc_router
    _clear_device_memory()
    return out


def run_pp():
    """Pipeline-schedule efficiency microbench (VERDICT r3 #3): wall
    time per step, remat vs store-activations, on a 1-stage mesh on the
    real chip (isolates the remat compute overhead — the bubble itself
    is analytic, reported from the schedule tables)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.pp_schedule import (
        build_pipeline_schedule, pipeline_forward_backward)

    rng = np.random.RandomState(0)
    d, ff, m, tokens, heads = 1024, 4096, 8, 512, 8
    hd = d // heads
    mesh = Mesh(np.array(jax.devices()[:1]), ("pp",))

    def w(*shape, s=0.02):
        return jnp.asarray(rng.randn(1, 1, *shape).astype(np.float32)
                           * s).astype(jnp.bfloat16)

    # a representative transformer block: attention remat is the
    # expensive part (an MLP-only stage remats for free under XLA —
    # recompute hides behind HBM traffic)
    params = {"wq": w(d, d), "wk": w(d, d), "wv": w(d, d),
              "wo": w(d, d), "w1": w(d, ff), "w2": w(ff, d)}

    def stage_fn(pj, x):
        t = x.shape[0]
        q = (x @ pj["wq"]).reshape(t, heads, hd)
        k = (x @ pj["wk"]).reshape(t, heads, hd)
        v = (x @ pj["wv"]).reshape(t, heads, hd)
        s = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) \
            / np.sqrt(hd)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None], s, -1e30)
        a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        att = jnp.einsum("hqk,khd->qhd", a, v).reshape(t, d)
        h = x + att @ pj["wo"]
        return (h + jax.nn.gelu(h @ pj["w1"]) @ pj["w2"]).astype(x.dtype)

    lp = {"h": jnp.zeros((d,), jnp.bfloat16)}

    def loss_fn(lpp, y, t):
        return jnp.mean(((y + t) @ lpp["h"]).astype(jnp.float32) ** 2)

    xs = jnp.asarray(rng.randn(m, tokens, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    ys = xs
    sched = build_pipeline_schedule(1, m, 1, "1F1B")
    out = {}
    for remat in (True, False):
        def f_(p_, l_, x_, y_, r=remat):
            loss, gs, glp, dxs = pipeline_forward_backward(
                stage_fn, loss_fn, p_, l_, x_, y_, mesh, sched, remat=r)
            # keep the backward live (a loss-only return lets XLA DCE
            # the whole gradient computation)
            gnorm = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in jax.tree_util.tree_leaves(gs))
            return loss, gnorm

        def make(iters):
            def many(p_, l_, x_, y_):
                def body(c, _):
                    # thread the carry into the inputs — a loop-invariant
                    # body would be hoisted out of the scan and run ONCE
                    loss, gn = f_(p_, l_,
                                  x_ + (c * 1e-24).astype(x_.dtype), y_)
                    return c + gn + loss, None
                tot, _ = jax.lax.scan(body, jnp.float32(0), None,
                                      length=iters)
                return tot
            return jax.jit(many)
        ms = _timed_scan_diff(make, 10, params, lp, xs, ys) * 1e3
        out["pp_step_ms_remat" if remat else "pp_step_ms_store"] = \
            round(ms, 2)
    if out["pp_step_ms_store"] >= 0.01:
        out["pp_remat_overhead_x"] = round(
            out["pp_step_ms_remat"] / out["pp_step_ms_store"], 3)
    else:
        # a collapsed dispatch diff (timing noise swallowed the delta)
        # must not crash the suite — flag it instead
        out["pp_remat_overhead_x"] = None
        out["pp_timing_note"] = "store-mode dispatch diff collapsed"
    # analytic bubble (cost-aware: the engine cond-skips invalid slots,
    # so a tick costs what its busiest stage runs — see
    # PipelineSchedule.tick_costs)
    for p, mm, v in ((4, 16, 1), (8, 32, 1), (4, 16, 2)):
        s = build_pipeline_schedule(p, mm, v, "1F1B")
        out[f"pp_bubble_p{p}m{mm}v{v}"] = round(s.bubble_overhead(), 4)
    out.update(_pp_bubble_measured(stage_fn, params, xs,
                                   build_pipeline_schedule))
    return out


def _timed_scan_diff(make, length, *args, calls=(2, 12), repeats=4):
    """Per-iteration wall time of a scanned program (tunnel round trip
    cancelled — see paddle_tpu.utils.timing)."""
    from paddle_tpu.utils.timing import timed_dispatch_diff
    return timed_dispatch_diff(make(length), args, calls=calls,
                               repeats=repeats, per_call=length)


def _pp_bubble_measured(stage_fn, params, xs, build_pipeline_schedule):
    """MEASURED tick-trace bubble at p4/m16/v1 (VERDICT r3 #1). A 4-chip
    wall time cannot be measured on one chip, so measure the two tick
    programs the cond-skipping engine actually runs ON this chip — a
    fwd-only tick and a steady fwd+bwd (remat) tick — and trace the
    p4/m16/v1 schedule tables with those measured costs:
    T = sum_t max_s(fwd_valid*t_f + bwd_valid*t_b). The single-chip
    measurement excludes ppermute latency (one [tokens, d] bf16 hop per
    tick over ICI, bandwidth-trivial next to a chunk's compute)."""
    import jax
    import jax.numpy as jnp

    pj = jax.tree_util.tree_map(lambda a: a[0, 0], params)
    x0 = xs[0]
    g0 = jnp.zeros(x0.shape, x0.dtype)

    def make_fwd(iters):
        def fwd_only(p_, c0):
            def body(c, _):
                return stage_fn(p_, c), None
            y, _ = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))
        return jax.jit(fwd_only)

    def make_pair(iters):
        def tick_pair(p_, c0):
            def body(c, _):
                out = stage_fn(p_, c)                 # fwd slot
                # perturb the bwd-slot input: with the SAME input, XLA
                # CSEs vjp's internal forward with the fwd slot above —
                # the real engine's fwd/bwd slots hold different
                # microbatches, so no such sharing exists
                _, vjp = jax.vjp(stage_fn, p_, c * 1.001)
                dp, dx = vjp(g0)
                gn = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree_util.tree_leaves(dp))
                return out + dx * 1e-9, gn
            y, gns = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32)) + jnp.sum(gns)
        return jax.jit(tick_pair)

    def make_bx(iters):
        """fwd + input-grad only (the zero-bubble B slot): the unused
        dp return lets XLA DCE the weight-grad matmuls."""
        def prog(p_, c0):
            def body(c, _):
                _, vjp = jax.vjp(stage_fn, p_, c * 1.001)
                dp, dx = vjp(g0 + c * 1e-9)
                return c + dx * 1e-9, None
            y, _ = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))
        return jax.jit(prog)

    def make_bw(iters):
        """fwd + weight-grad only (the zero-bubble W slot)."""
        def prog(p_, c0):
            def body(c, _):
                _, vjp = jax.vjp(stage_fn, p_, c * 1.001)
                dp, dx = vjp(g0 + c * 1e-9)
                gn = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree_util.tree_leaves(dp))
                return c + gn.astype(c.dtype) * 1e-24, None
            y, _ = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))
        return jax.jit(prog)

    t_f = _timed_scan_diff(make_fwd, 32, pj, x0)
    t_fb = _timed_scan_diff(make_pair, 32, pj, x0)
    t_b = max(t_fb - t_f, 1e-9)
    t_bx = max(_timed_scan_diff(make_bx, 32, pj, x0) - t_f, 1e-9)
    t_bw = max(_timed_scan_diff(make_bw, 32, pj, x0) - t_f, 1e-9)

    out = {"pp_tick_fwd_ms": round(t_f * 1e3, 3),
           "pp_tick_bwd_ms": round(t_b * 1e3, 3),
           "pp_tick_bx_ms": round(t_bx * 1e3, 3),
           "pp_tick_bw_ms": round(t_bw * 1e3, 3),
           # cost-model validation (VERDICT r4 #5): the tick tables
           # price a remat bwd at 3 fwd units; the measured ratio says
           # how true that is for a real transformer block
           "pp_bwd_over_fwd_measured": round(t_b / t_f, 3)}
    for p, mm, v in ((4, 16, 1), (4, 16, 2)):
        s = build_pipeline_schedule(p, mm, v, "1F1B")
        fv = s.tables["fwd_valid"].astype(np.float64)
        bv = s.tables["bwd_valid"].astype(np.float64)
        total = (fv * t_f + bv * t_b).max(axis=1).sum()
        ideal = s.n_micro * s.vpp * (t_f + t_b)
        out[f"pp_bubble_measured_p{p}m{mm}v{v}"] = round(
            1.0 - ideal / total, 4)
    # zero-bubble schedule, measured with its own split-slot costs
    # (store mode: B and W run off stored residuals, no remat fwd)
    s = build_pipeline_schedule(4, 16, 1, "zb")
    fv = s.tables["fwd_valid"].astype(np.float64)
    bv = s.tables["bwd_valid"].astype(np.float64)
    wv = s.tables["w_valid"].astype(np.float64)
    total = (fv * t_f + bv * t_bx + wv * t_bw).max(axis=1).sum()
    ideal = s.n_micro * (t_f + t_bx + t_bw)
    out["pp_bubble_measured_p4m16zb"] = round(1.0 - ideal / total, 4)
    out["pp_bubble_p4m16zb"] = round(s.bubble_overhead(), 4)
    # honest net-wall comparison (zb vs 1F1B-store at p4/m16): the
    # block-granularity vjp split duplicates the shared cotangent
    # chain (t_bx + t_bw > t_b_store), so the smaller bubble does not
    # automatically mean a faster step — this ratio is the verdict.
    # zb pays off when a stage's dw does not share a backward chain
    # with dx (single-matmul stages), not for full transformer blocks.
    s1 = build_pipeline_schedule(4, 16, 1, "1F1B")
    f1 = s1.tables["fwd_valid"].astype(np.float64)
    b1 = s1.tables["bwd_valid"].astype(np.float64)
    t_b_store = max(t_b - t_f, 1e-9)   # store mode skips the remat fwd
    total_store = (f1 * t_f + b1 * t_b_store).max(axis=1).sum()
    out["pp_zb_net_wall_ratio_vs_store"] = round(total / total_store, 3)
    return out


def _clear_device_memory():
    """Drop every live device array (callers rebuild their model/engine
    from scratch) and clear the jit caches that keep dead engines'
    arrays pinned, so the next suite/leg starts from a clean HBM pool."""
    import gc
    import jax
    gc.collect()
    for arr in jax.live_arrays():
        arr.delete()
    jax.clear_caches()


def _suite_barrier(tag, out):
    """Inter-suite HBM barrier (BENCH_r04 lesson: one OOM'd suite
    poisoned every later serving row with RESOURCE_EXHAUSTED after
    mid8k). Records the suite's peak-memory watermark, then clears
    device memory via _clear_device_memory. The TPU runtime's
    peak_bytes_in_use is a process-lifetime high-water mark (not
    resettable), so per-suite attribution reads as the JUMP between
    consecutive rows; CPU backends report no memory_stats and just
    skip the rows."""
    import jax
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        ms = {}
    if "peak_bytes_in_use" in ms:
        out[f"{tag}_peak_bytes_in_use"] = int(ms["peak_bytes_in_use"])
    if "bytes_in_use" in ms:
        out[f"{tag}_bytes_in_use"] = int(ms["bytes_in_use"])
    _clear_device_memory()


def run_serving_suite():
    """bf16 and int8 at c8 (the r4 open-loop protocol compiles 3 prompt
    buckets x 2 prefill widths per engine, so the c4 rows were dropped
    to keep the auto-suite bounded; c4 behavior is covered by tests)."""
    out = {}
    for wd in (None, "int8"):
        out.update(run_serving(weight_dtype=wd, concurrency=8))
        _suite_barrier(f"serving_{'int8' if wd else 'bf16'}_c8", out)
    for wd in (None, "int8", "int4"):
        out.update(run_serving_capacity(concurrency=8, weight_dtype=wd))
        _suite_barrier("serving_capacity" if wd is None
                       else f"serving_capacity_{wd}", out)
    # shared-prefix A/B (automatic prefix caching): same serving-mode
    # timeout budget — two small engines, 8 requests each
    out.update(run_serving_prefix())
    _suite_barrier("serving_prefix", out)
    # chunked-prefill A/B (stall-free interleaving): long prompt into a
    # running decode stream, ITL p99 of the running requests
    out.update(run_serving_interleave())
    _suite_barrier("serving_interleave", out)
    # fault-tolerance A/B (deadlines + shedding under an overloaded
    # burst): goodput and deadline-miss rate, on vs off
    out.update(run_serving_degradation())
    _suite_barrier("serving_degradation", out)
    # ragged unified prefill+decode A/B: device dispatches per
    # delivered token, one program per step vs the dense schedule
    out.update(run_serving_ragged())
    _suite_barrier("serving_ragged", out)
    # telemetry overhead A/B (ISSUE 12): tracer on/off on the ragged
    # row — < 5% tok/s overhead asserted in-row, tokens bit-identical,
    # flight recorder exported as the bench artifact
    out.update(run_serving_trace())
    _suite_barrier("serving_trace", out)
    # quantized KV cache A/B (ISSUE 13): accuracy at equal geometry
    # (token identity + logits rel-error probe, bytes/token reduction)
    # and capacity at equal pool HBM bytes (strictly fewer
    # OOM-preemptions on the oversubscribed burst)
    out.update(run_serving_kv8())
    _suite_barrier("serving_kv8", out)
    # multi-step fused decode A/B (ISSUE 16): k=1 vs k=4 on the pinned
    # greedy workload — >= 3x fewer dispatches per delivered token at
    # equal-or-better tok/s, token identity asserted in-row, sampled
    # host_schedule+dispatch_queue share reported per leg
    out.update(run_serving_msteps())
    _suite_barrier("serving_msteps", out)
    # speculative decoding A/B (ISSUE 9): repetitive vs adversarial
    # workloads, spec on/off — tok/s, ITL, acceptance rate, token
    # identity asserted inside the row
    out.update(run_serving_spec())
    _suite_barrier("serving_spec", out)
    # multi-chip TP A/B (ISSUE 8): the sharded ragged step at tp=1/2/4,
    # fp32 vs int8 comms — skipped cleanly when the process' backend
    # cannot provide the 8-device mesh (e.g. initialized single-chip)
    out.update(run_serving_tp())
    _suite_barrier("serving_tp", out)
    # multi-tenant many-LoRA A/B (ISSUE 10): mixed-tenant 8-stream
    # workload (4 adapters) vs base-only — lora overhead, adapter hit
    # rate, base-stream token identity asserted inside the row
    out.update(run_serving_lora())
    _suite_barrier("serving_lora", out)
    # process-per-replica fleet A/B (ISSUE 19): dp=2 workers in spawned
    # processes vs the inproc fleet vs one engine — token identity
    # asserted across all three legs, RPC+journal overhead bounded at
    # 10% tok/s, and a SIGKILL respawn wall-clock probe
    out.update(run_serving_proc())
    _suite_barrier("serving_proc", out)
    # engine-vs-raw account (r5): the decode chunks run FASTER per step
    # on device than the raw row (1.49 vs 1.80 ms measured via xprof);
    # the residual decode-phase gap is one ~85 ms tunnel RTT per chunk
    # boundary, which shrinks with chunk length and model size — the
    # 8B capacity row (paged_decode_8b) runs at 97% of raw decode.
    out["serving_capacity_note"] = (
        "decode chunk device time 1.49 ms/step < raw 1.80; residual "
        "gap = per-chunk tunnel RTT (~85 ms), amortized at 8B to 97%")
    return out


# ---------------------------------------------------------------------------
# auto-mode orchestrator (JAX-free parent; every row is a subprocess)
# ---------------------------------------------------------------------------

def _default_child_runner(mode, timeout):
    """Run `python bench.py <mode>` in a fresh process; return
    (parsed_json_or_None, stderr_tail). The parent never imports jax,
    so the chip is exclusively the child's."""
    env = os.environ.copy()
    # persistent XLA compile cache: retries and overlapping configs
    # skip recompiles
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   "/tmp/paddle_tpu_xla_cache")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if p.returncode != 0:
        # a crashed child's stdout may still contain dict-shaped noise
        # (structured log lines); never mistake it for a result
        return None, ((p.stderr or "") + (p.stdout or ""))[-400:]
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed, (p.stderr or "")[-400:]
    return None, ((p.stderr or "") + (p.stdout or ""))[-400:]


def _calibrate_with_retry(child_runner, backoff, notes):
    """Run the calibration probe until it lands in the plausible band,
    sleeping between attempts (the r4 poison was transient external HBM
    pressure — worth waiting out). Returns (cal_dict_or_None, ok)."""
    cal = None
    for i, pause in enumerate(backoff):
        if pause:
            time.sleep(pause)
        res, err = child_runner("calibrate", 600)
        if res is None:
            notes.append(f"calibration attempt {i}: crashed: {err}")
            continue
        cal = res.get("extra", res)
        if cal.get("calibration_ok"):
            return cal, True
        notes.append(
            f"calibration attempt {i}: frac_peak="
            f"{cal.get('calibration_frac_peak')} outside band {CAL_BAND}")
    return cal, False


def run_auto(child_runner=None, backoff=None):
    """Subprocess-isolated full suite with calibration gating.

    Flow: calibrate (retry w/ backoff; never-ok -> env_suspect JSON with
    NO perf rows) -> headline -> each AUTO_MODE in its own process. A
    mode that fails or lands <30% of last-known-good is retried ONCE
    after re-calibrating; if re-calibration fails, the environment died
    mid-suite -> stop, flag env_suspect, report what was captured."""
    child_runner = child_runner or _default_child_runner
    backoff = (0, 30, 60, 120) if backoff is None else backoff
    notes = []

    cal, cal_ok = _calibrate_with_retry(child_runner, backoff, notes)
    if not cal_ok:
        return {
            "metric": "llama_mid_train_tokens_per_sec_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "env_suspect": True,
            "extra": {
                "env_suspect_reason":
                    "calibration matmul never reached the plausible "
                    "band; perf rows withheld (r4 lesson: a poisoned "
                    "environment must not be recorded as a slow code)",
                "calibration": cal, "notes": notes,
            },
        }

    env_suspect = False

    def _is_transient(err):
        """Known tunnel stream drop (seen several times per session):
        the chip is fine, the RPC died — worth same-mode retries before
        the recalibrate path burns a backoff cycle. Anchored on the
        full stream-drop signature: EVERY remote error mentions the
        remote_compile endpoint, including deterministic ones that
        must not be re-run at full timeout."""
        return "response body closed" in (err or "")

    def run_mode(mode):
        """(result, suspect) with one recalibrate+retry on fail/slow."""
        nonlocal env_suspect
        timeout = MODE_TIMEOUT_S.get(mode, DEFAULT_TIMEOUT_S)
        res, err = child_runner(mode, timeout)
        for _ in range(2):
            if res is not None or not _is_transient(err):
                break
            notes.append(f"{mode}: transient tunnel fault, retrying")
            res, err = child_runner(mode, timeout)
        ratio = _lkg_ratio(mode, res) if res else None
        if res is not None and (ratio is None or ratio >= 0.3):
            return res, False
        notes.append(f"{mode}: first attempt "
                     + (f"slow (lkg_ratio={ratio})" if res else
                        f"failed: {err}"))
        recal, ok = _calibrate_with_retry(child_runner, backoff[:2],
                                          notes)
        if not ok:
            env_suspect = True
            notes.append(f"{mode}: re-calibration failed -> environment "
                         "broke mid-suite")
            return res, res is not None
        res2, err2 = child_runner(mode, timeout)
        for _ in range(2):
            if res2 is not None or not _is_transient(err2):
                break
            notes.append(f"{mode}: transient tunnel fault on retry, "
                         "retrying")
            res2, err2 = child_runner(mode, timeout)
        ratio2 = _lkg_ratio(mode, res2) if res2 else None
        if res2 is not None:
            return res2, bool(ratio2 is not None and ratio2 < 0.3)
        notes.append(f"{mode}: retry failed: {err2}")
        return res, res is not None

    headline_mode = "mid"
    result, headline_suspect = run_mode("mid")
    if result is None and not env_suspect:
        # only fall back to the small config while the environment
        # still calibrates clean — a dead env would just burn ~30 min
        # and record small's number as the headline
        headline_mode = "small"
        result, headline_suspect = run_mode("small")
    if result is None:
        return {
            "metric": "llama_mid_train_tokens_per_sec_chip",
            "value": 0.0, "unit": "tokens/s/chip",
            "vs_baseline": 0.0, "env_suspect": True,
            "extra": {"env_suspect_reason":
                      ("environment broke during the headline attempt"
                       if env_suspect else
                       "headline failed twice after good calibration"),
                      "calibration": cal, "notes": notes},
        }
    result.setdefault("extra", {})
    ex = result["extra"]
    headline_ratio = _lkg_ratio(headline_mode, result)
    if headline_suspect:
        ex["headline_suspect"] = True

    on_cpu = cal.get("calibration_platform") == "cpu"
    for mode in AUTO_MODES:
        if env_suspect:
            notes.append(f"{mode}: skipped (environment flagged suspect)")
            continue
        if on_cpu and mode in ("8b", "profile"):
            # CPU auto runs (harness tests, dev boxes): an 8B-geometry
            # decode would burn the whole mode timeout and the profile
            # assertion requires device lanes — skip, don't fail
            notes.append(f"{mode}: skipped (cpu backend)")
            continue
        t0 = time.perf_counter()
        child, suspect = run_mode(mode)
        if child is None:
            ex[f"{mode}_error"] = notes[-1] if notes else "failed"
            continue
        if mode in ("mid4k", "mid8k", "1b"):
            ce = child.get("extra", {})
            ex[f"llama_{mode}_tok_per_sec"] = child.get("value")
            ex[f"llama_{mode}_mfu"] = ce.get("mfu")
            ex[f"llama_{mode}_params"] = ce.get("params")
            ex[f"llama_{mode}_step_ms"] = ce.get("step_ms")
        else:
            ce = dict(child.get("extra") or {})
            # each child stamps its own extra["lkg_ratio"] via main();
            # merged as-is it would clobber the headline's — rename to
            # the per-mode key instead
            ce.pop("lkg_ratio", None)
            ex.update(ce)
        ratio = _lkg_ratio(mode, child)
        if ratio is not None:
            ex[f"{mode}_lkg_ratio"] = ratio
        if suspect:
            ex[f"{mode}_suspect"] = True
        ex[f"{mode}_bench_s"] = round(time.perf_counter() - t0, 1)

    ex["lkg_ratio"] = headline_ratio
    ex["calibration_tflops"] = cal.get("calibration_tflops")
    ex["calibration_frac_peak"] = cal.get("calibration_frac_peak")
    if notes:
        ex["notes"] = notes
    result["env_suspect"] = env_suspect
    return result


def main(mode: str):
    if mode in ("mid", "mid4k", "mid8k", "1b", "small", "tiny"):
        result = run_llama(mode)
    elif mode == "calibrate":
        r = run_calibration()
        result = {"metric": "calibration_tflops", "unit": "TFLOP/s",
                  "value": r["calibration_tflops"],
                  "vs_baseline": r.get("calibration_frac_peak") or 0.0,
                  "extra": r}
    elif mode == "resnet":
        r = run_resnet()
        result = {"metric": "resnet50_train_imgs_per_sec_chip",
                  "unit": "imgs/s/chip",
                  "value": r["resnet50_imgs_per_sec"], "extra": r}
    elif mode == "decode":
        r = run_decode()
        result = {"metric": "paged_decode_tokens_per_sec",
                  "unit": "tokens/s",
                  "value": r["paged_decode_tok_per_sec"], "extra": r}
    elif mode == "serving":
        r = run_serving_suite()
        result = {"metric": "serving_bf16_c8_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r["serving_bf16_c8_tok_per_sec"], "extra": r}
    elif mode == "serving_interleave":
        r = run_serving_interleave()
        result = {"metric": "serving_interleave_itl_p99_improvement_x",
                  "unit": "x",
                  "value": r["serving_interleave_itl_p99_improvement_x"],
                  "extra": r}
    elif mode == "serving_degradation":
        r = run_serving_degradation()
        result = {"metric": "serving_degradation_goodput_x",
                  "unit": "x",
                  "value": r["serving_degradation_goodput_x"],
                  "extra": r}
    elif mode == "serving_ragged":
        r = run_serving_ragged()
        result = {"metric": "serving_ragged_dispatch_reduction_x",
                  "unit": "x",
                  "value": r["serving_ragged_dispatch_reduction_x"],
                  "extra": r}
    elif mode == "serving_trace":
        r = run_serving_trace()
        result = {"metric": "serving_trace_overhead_frac",
                  "unit": "frac",
                  "value": r["serving_trace_overhead_frac"],
                  "extra": r}
    elif mode == "serving_kv8":
        r = run_serving_kv8()
        result = {"metric": "serving_kv8_bytes_per_token_reduction_x",
                  "unit": "x",
                  "value": r["serving_kv8_bytes_per_token_reduction_x"],
                  "extra": r}
    elif mode == "serving_msteps":
        r = run_serving_msteps()
        result = {"metric": "serving_msteps_dispatch_reduction_x",
                  "unit": "x",
                  "value": r["serving_msteps_dispatch_reduction_x"],
                  "extra": r}
    elif mode == "serving_spec":
        r = run_serving_spec()
        result = {"metric": "serving_spec_rep_speedup_x",
                  "unit": "x",
                  "value": r["serving_spec_rep_speedup_x"],
                  "extra": r}
    elif mode == "serving_tp":
        r = run_serving_tp()
        result = {"metric": "serving_tp2_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r.get("serving_tp2_tok_per_sec", 0.0),
                  "extra": r}
    elif mode == "serving_lora":
        r = run_serving_lora()
        result = {"metric": "serving_lora_lora_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r.get("serving_lora_lora_tok_per_sec", 0.0),
                  "extra": r}
    elif mode == "serving_dp":
        r = run_serving_dp()
        result = {"metric": "serving_dp2_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r.get("serving_dp2_tok_per_sec", 0.0),
                  "extra": r}
    elif mode == "serving_proc":
        r = run_serving_proc()
        result = {"metric": "serving_proc_process_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r.get("serving_proc_process_tok_per_sec",
                                 0.0),
                  "extra": r}
    elif mode == "pp":
        r = run_pp()
        result = {"metric": "pp_remat_overhead_x", "unit": "x",
                  "value": r["pp_remat_overhead_x"], "extra": r}
    elif mode == "dit":
        r = run_dit()
        result = {"metric": "dit_xl2_imgs_per_sec", "unit": "imgs/s",
                  "value": r["dit_xl2_imgs_per_sec"], "extra": r}
    elif mode == "moe":
        r = run_moe()
        result = {"metric": "moe_ragged_tok_per_sec", "unit": "tokens/s",
                  "value": r["moe_ragged_tok_per_sec"], "extra": r}
    elif mode == "8b":
        r = run_8b()
        result = {"metric": "paged_decode_8b_int4_tok_per_sec",
                  "unit": "tokens/s",
                  "value": r["paged_decode_8b_int4_tok_per_sec"],
                  "extra": r}
    elif mode == "profile":
        r = run_profile()
        result = {"metric": "profile_device_events", "unit": "events",
                  "value": r["profile_device_events"], "extra": r}
    else:  # auto: subprocess-isolated suite (see run_auto)
        return run_auto()
    # real per-mode vs_baseline (VERDICT r4 #8): ratio to the
    # last-known-good capture, so single-mode runs track trends
    if "vs_baseline" not in result:
        result["vs_baseline"] = _lkg_ratio(mode, result) or 0.0
    if "lkg_ratio" not in result.get("extra", {}):
        result.setdefault("extra", {})["lkg_ratio"] = \
            _lkg_ratio(mode, result)
    return result


_VALID_MODES = ("auto", "mid", "mid4k", "mid8k", "1b", "small", "tiny",
                "resnet", "decode", "8b", "serving",
                "serving_interleave", "serving_degradation",
                "serving_ragged", "serving_trace", "serving_spec",
                "serving_kv8", "serving_msteps", "serving_tp",
                "serving_lora", "serving_dp", "serving_proc", "pp",
                "moe", "dit", "profile", "calibrate")

if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "auto"
    if mode not in _VALID_MODES:
        sys.exit(f"unknown bench mode {mode!r}; expected one of "
                 f"{_VALID_MODES}")
    result = main(mode)
    print(json.dumps(result))
