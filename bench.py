"""Benchmark suite for one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Headline = Llama causal-LM training throughput (largest config that fits
the chip: llama_mid ~0.7B with GQA, fallback llama_small 0.5B), measured
as steady-state tokens/sec/chip with a compiled TrainStep (bf16 weights,
AdamW with f32 masters). vs_baseline = achieved_MFU / 0.40 (BASELINE.md
north star: >=40% MFU at Llama-3-8B class).

extra also records the two secondary benches BASELINE.md lists:
- resnet50_imgs_per_sec: ResNet-50 training imgs/sec/chip (bf16,
  momentum-SGD, batch 256)
- paged_decode_tok_per_sec: serving decode throughput over the paged KV
  cache (inference.paged_decode.PagedLlamaDecoder, Pallas scalar-prefetch
  decode kernel)

MFU accounting follows the PaLM-appendix convention:
  flops/token = 6*N_params + 12*L*H*Q*S  (attention term)
Peak chip flops: v5e = 197e12 bf16, v5p = 459e12.

Modes: `python bench.py [auto|mid|small|tiny|resnet|decode]` — auto (the
driver default) runs the full set.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def detect_peak_flops() -> float:
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    # default: v5e / "TPU v5 lite"
    return 197e12


def run_llama(config: str = "mid"):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import (LlamaForCausalLM, llama_1b, llama_mid,
                                   llama_small, llama_tiny)

    paddle.seed(0)
    if config == "mid":
        # ~0.7B, GQA 3:1; flash attention keeps activations light enough
        # to train without remat at batch 4
        cfg = llama_mid(dtype="bfloat16", use_recompute=False)
        batch, seq, iters = 4, 2048, 10
    elif config == "mid4k":
        # seq-4096 long-context row (BASELINE protocol): chunked CE
        # frees the [B,S,V] logits so b2 s4096 trains without remat
        cfg = llama_mid(dtype="bfloat16", use_recompute=False,
                        chunked_ce_tokens=1024,
                        max_position_embeddings=4096)
        batch, seq, iters = 2, 4096, 10
    elif config == "1b":
        # largest-fitting row: ~1.0B with remat + chunked CE. AdamW f32
        # masters for 1.0B are ~12GB of the 16GB chip — batch 4 is the
        # activation budget that remains
        cfg = llama_1b(dtype="bfloat16", use_recompute=True,
                       chunked_ce_tokens=1024)
        batch, seq, iters = 4, 2048, 10
    elif config == "small":
        cfg = llama_small(dtype="bfloat16", use_recompute=False)
        batch, seq, iters = 8, 1024, 10
    else:
        cfg = llama_tiny(dtype="bfloat16")
        batch, seq, iters = 8, 256, 10

    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                          weight_decay=0.01)
    step = paddle.jit.TrainStep(model, lambda o, l: model.loss(o, l), opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))

    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    final = float(loss)  # blocks
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    n_params = model.num_params()
    l_, h_, q_ = (cfg.num_hidden_layers, cfg.num_attention_heads,
                  cfg.hidden_size // cfg.num_attention_heads)
    flops_per_token = 6 * n_params + 12 * l_ * h_ * q_ * seq
    mfu = tokens_per_sec * flops_per_token / detect_peak_flops()
    return {
        "metric": f"llama_{config}_train_tokens_per_sec_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "final_loss": round(final, 4),
            "step_ms": round(1000 * dt / iters, 2),
        },
    }


def run_resnet():
    """ResNet-50 training imgs/sec/chip (BASELINE.md secondary metric)."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.vision.models import resnet50
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    for p in model.parameters():  # bf16 weights, f32 masters in SGD
        p._replace(p._value.astype("bfloat16"))
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda o, l: F.cross_entropy(o.astype("float32"), l), opt)

    batch, iters = 256, 10
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(
        rng.randn(batch, 3, 224, 224).astype(np.float32)).astype("bfloat16")
    y = paddle.to_tensor(rng.randint(0, 1000, batch).astype(np.int64))
    for _ in range(2):
        loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    float(loss)
    dt = time.perf_counter() - t0
    return {"resnet50_imgs_per_sec": round(batch * iters / dt, 1),
            "resnet50_step_ms": round(1000 * dt / iters, 2)}


def run_decode():
    """Paged-KV serving decode tokens/sec (Pallas decode kernel)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference.paged_decode import PagedLlamaDecoder

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    batch, prompt, steps = 8, 512, 64
    block_size = 64
    dec = PagedLlamaDecoder(
        model, num_blocks=(prompt + steps + block_size) * batch // block_size
        + batch, block_size=block_size)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    # warmup with the SAME token count (the scanned decode loop's length
    # is a compile-time constant)
    dec.generate(ids, max_new_tokens=steps)
    timings = {}
    out = dec.generate(ids, max_new_tokens=steps, timings=timings)
    dt = timings["decode_s"]            # decode phase only — the prefill
    assert out.shape == (batch, prompt + steps)   # is reported separately
    return {"paged_decode_tok_per_sec": round(batch * (steps - 1) / dt, 1),
            "paged_decode_batch": batch,
            "paged_decode_ms_per_step": round(1000 * dt / (steps - 1), 2),
            "prefill_ms": round(1000 * timings["prefill_s"], 2)}


def run_serving(weight_dtype=None, concurrency=8):
    """Continuous-batching serving bench (VERDICT r3 protocol): mixed
    prompt lengths, 2x oversubscribed request queue; reports tok/s and
    p50/p99 request latency."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_small
    from paddle_tpu.inference import ServingEngine, SamplingParams

    paddle.seed(0)
    cfg = llama_small(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    model.eval()
    block_size = 64
    new_tokens = 64
    n_requests = concurrency * 2
    eng = ServingEngine(
        model, max_batch_size=concurrency,
        num_blocks=concurrency * ((512 + new_tokens) // block_size + 2) + 1,
        block_size=block_size, prompt_buckets=(512,),
        weight_dtype=weight_dtype, chunk_size=16)
    rng = np.random.RandomState(0)
    lens = rng.randint(128, 513, n_requests)
    # warmup: compile prefill + decode with one short request
    eng.warmup(prompt_len=512)  # compiles (both prefill widths +
    # decode chunk) must not skew the measured stats
    t0 = time.perf_counter()
    for l in lens:
        eng.add_request(rng.randint(0, cfg.vocab_size, int(l)),
                        SamplingParams(max_new_tokens=new_tokens))
    eng.run_to_completion()
    dt = time.perf_counter() - t0
    st = eng.stats()
    gen = st["generated_tokens"]
    tag = f"serving_{'int8' if weight_dtype else 'bf16'}_c{concurrency}"
    return {
        f"{tag}_tok_per_sec": round(gen / dt, 1),
        f"{tag}_latency_p50_s": round(st["latency_p50_s"], 3),
        f"{tag}_latency_p99_s": round(st["latency_p99_s"], 3),
        f"{tag}_ttft_p50_s": round(st["ttft_p50_s"], 3),
    }


def run_pp():
    """Pipeline-schedule efficiency microbench (VERDICT r3 #3): wall
    time per step, remat vs store-activations, on a 1-stage mesh on the
    real chip (isolates the remat compute overhead — the bubble itself
    is analytic, reported from the schedule tables)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.pp_schedule import (
        build_pipeline_schedule, pipeline_forward_backward)

    rng = np.random.RandomState(0)
    d, ff, m, tokens, heads = 1024, 4096, 8, 512, 8
    hd = d // heads
    mesh = Mesh(np.array(jax.devices()[:1]), ("pp",))

    def w(*shape, s=0.02):
        return jnp.asarray(rng.randn(1, 1, *shape).astype(np.float32)
                           * s).astype(jnp.bfloat16)

    # a representative transformer block: attention remat is the
    # expensive part (an MLP-only stage remats for free under XLA —
    # recompute hides behind HBM traffic)
    params = {"wq": w(d, d), "wk": w(d, d), "wv": w(d, d),
              "wo": w(d, d), "w1": w(d, ff), "w2": w(ff, d)}

    def stage_fn(pj, x):
        t = x.shape[0]
        q = (x @ pj["wq"]).reshape(t, heads, hd)
        k = (x @ pj["wk"]).reshape(t, heads, hd)
        v = (x @ pj["wv"]).reshape(t, heads, hd)
        s = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) \
            / np.sqrt(hd)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None], s, -1e30)
        a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        att = jnp.einsum("hqk,khd->qhd", a, v).reshape(t, d)
        h = x + att @ pj["wo"]
        return (h + jax.nn.gelu(h @ pj["w1"]) @ pj["w2"]).astype(x.dtype)

    lp = {"h": jnp.zeros((d,), jnp.bfloat16)}

    def loss_fn(lpp, y, t):
        return jnp.mean(((y + t) @ lpp["h"]).astype(jnp.float32) ** 2)

    xs = jnp.asarray(rng.randn(m, tokens, d).astype(np.float32)) \
        .astype(jnp.bfloat16)
    ys = xs
    sched = build_pipeline_schedule(1, m, 1, "1F1B")
    out = {}
    for remat in (True, False):
        def f_(p_, l_, x_, y_, r=remat):
            loss, gs, glp, dxs = pipeline_forward_backward(
                stage_fn, loss_fn, p_, l_, x_, y_, mesh, sched, remat=r)
            # keep the backward live (a loss-only return lets XLA DCE
            # the whole gradient computation)
            gnorm = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in jax.tree_util.tree_leaves(gs))
            return loss, gnorm

        def make(iters):
            def many(p_, l_, x_, y_):
                def body(c, _):
                    # thread the carry into the inputs — a loop-invariant
                    # body would be hoisted out of the scan and run ONCE
                    loss, gn = f_(p_, l_,
                                  x_ + (c * 1e-24).astype(x_.dtype), y_)
                    return c + gn + loss, None
                tot, _ = jax.lax.scan(body, jnp.float32(0), None,
                                      length=iters)
                return tot
            return jax.jit(many)
        ms = _timed_scan_diff(make, 10, params, lp, xs, ys) * 1e3
        out["pp_step_ms_remat" if remat else "pp_step_ms_store"] = \
            round(ms, 2)
    out["pp_remat_overhead_x"] = round(
        out["pp_step_ms_remat"] / out["pp_step_ms_store"], 3)
    # analytic bubble (cost-aware: the engine cond-skips invalid slots,
    # so a tick costs what its busiest stage runs — see
    # PipelineSchedule.tick_costs)
    for p, mm, v in ((4, 16, 1), (8, 32, 1), (4, 16, 2)):
        s = build_pipeline_schedule(p, mm, v, "1F1B")
        out[f"pp_bubble_p{p}m{mm}v{v}"] = round(s.bubble_overhead(), 4)
    out.update(_pp_bubble_measured(stage_fn, params, xs,
                                   build_pipeline_schedule))
    return out


def _timed_scan_diff(make, length, *args, calls=(2, 12), repeats=4):
    """Per-iteration wall time of a scanned program (tunnel round trip
    cancelled — see paddle_tpu.utils.timing)."""
    from paddle_tpu.utils.timing import timed_dispatch_diff
    return timed_dispatch_diff(make(length), args, calls=calls,
                               repeats=repeats, per_call=length)


def _pp_bubble_measured(stage_fn, params, xs, build_pipeline_schedule):
    """MEASURED tick-trace bubble at p4/m16/v1 (VERDICT r3 #1). A 4-chip
    wall time cannot be measured on one chip, so measure the two tick
    programs the cond-skipping engine actually runs ON this chip — a
    fwd-only tick and a steady fwd+bwd (remat) tick — and trace the
    p4/m16/v1 schedule tables with those measured costs:
    T = sum_t max_s(fwd_valid*t_f + bwd_valid*t_b). The single-chip
    measurement excludes ppermute latency (one [tokens, d] bf16 hop per
    tick over ICI, bandwidth-trivial next to a chunk's compute)."""
    import jax
    import jax.numpy as jnp

    pj = jax.tree_util.tree_map(lambda a: a[0, 0], params)
    x0 = xs[0]
    g0 = jnp.zeros(x0.shape, x0.dtype)

    def make_fwd(iters):
        def fwd_only(p_, c0):
            def body(c, _):
                return stage_fn(p_, c), None
            y, _ = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))
        return jax.jit(fwd_only)

    def make_pair(iters):
        def tick_pair(p_, c0):
            def body(c, _):
                out = stage_fn(p_, c)                 # fwd slot
                # perturb the bwd-slot input: with the SAME input, XLA
                # CSEs vjp's internal forward with the fwd slot above —
                # the real engine's fwd/bwd slots hold different
                # microbatches, so no such sharing exists
                _, vjp = jax.vjp(stage_fn, p_, c * 1.001)
                dp, dx = vjp(g0)
                gn = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree_util.tree_leaves(dp))
                return out + dx * 1e-9, gn
            y, gns = jax.lax.scan(body, c0, None, length=iters)
            return jnp.sum(y.astype(jnp.float32)) + jnp.sum(gns)
        return jax.jit(tick_pair)

    t_f = _timed_scan_diff(make_fwd, 32, pj, x0)
    t_fb = _timed_scan_diff(make_pair, 32, pj, x0)
    t_b = max(t_fb - t_f, 1e-9)

    s = build_pipeline_schedule(4, 16, 1, "1F1B")
    fv = s.tables["fwd_valid"].astype(np.float64)
    bv = s.tables["bwd_valid"].astype(np.float64)
    total = (fv * t_f + bv * t_b).max(axis=1).sum()
    ideal = s.n_micro * s.vpp * (t_f + t_b)
    return {"pp_bubble_measured_p4m16v1": round(1.0 - ideal / total, 4),
            "pp_tick_fwd_ms": round(t_f * 1e3, 3),
            "pp_tick_bwd_ms": round(t_b * 1e3, 3)}


def run_serving_suite():
    """fp and int8 at two concurrency levels."""
    out = {}
    for wd in (None, "int8"):
        for conc in (4, 8):
            out.update(run_serving(weight_dtype=wd, concurrency=conc))
    return out


def main(mode: str):
    if mode in ("mid", "mid4k", "1b", "small", "tiny"):
        result = run_llama(mode)
    elif mode == "resnet":
        result = {"metric": "resnet50_train_imgs_per_sec_chip",
                  "unit": "imgs/s/chip", "vs_baseline": 0.0}
        result.update({"value": run_resnet()["resnet50_imgs_per_sec"]})
    elif mode == "decode":
        r = run_decode()
        result = {"metric": "paged_decode_tokens_per_sec",
                  "unit": "tokens/s", "vs_baseline": 0.0,
                  "value": r["paged_decode_tok_per_sec"], "extra": r}
    elif mode == "serving":
        r = run_serving_suite()
        result = {"metric": "serving_bf16_c8_tok_per_sec",
                  "unit": "tokens/s", "vs_baseline": 0.0,
                  "value": r["serving_bf16_c8_tok_per_sec"], "extra": r}
    elif mode == "pp":
        r = run_pp()
        result = {"metric": "pp_remat_overhead_x", "unit": "x",
                  "vs_baseline": 0.0, "value": r["pp_remat_overhead_x"],
                  "extra": r}
    else:  # auto: headline llama + secondary benches in extra
        try:
            result = run_llama("mid")
        except Exception as e:
            sys.stderr.write(f"bench mid failed ({e}); retrying small\n")
            result = run_llama("small")
        # BASELINE protocol rows: long-context + largest-fitting configs
        import gc
        for cfg_name in ("mid4k", "1b"):
            try:
                r = run_llama(cfg_name)
                result["extra"][f"llama_{cfg_name}_tok_per_sec"] = \
                    r["value"]
                result["extra"][f"llama_{cfg_name}_mfu"] = \
                    r["extra"]["mfu"]
                result["extra"][f"llama_{cfg_name}_params"] = \
                    r["extra"]["params"]
            except Exception as e:
                sys.stderr.write(f"bench {cfg_name} failed: {e}\n")
            gc.collect()  # release the failed attempt's HBM promptly
        for name, fn in (("resnet", run_resnet), ("decode", run_decode),
                         ("serving", run_serving_suite), ("pp", run_pp)):
            try:
                result["extra"].update(fn())
            except Exception as e:
                sys.stderr.write(f"bench {name} failed: {e}\n")
            gc.collect()
    return result


_VALID_MODES = ("auto", "mid", "mid4k", "1b", "small", "tiny", "resnet",
                "decode", "serving", "pp")

if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "auto"
    if mode not in _VALID_MODES:
        sys.exit(f"unknown bench mode {mode!r}; expected one of "
                 f"{_VALID_MODES}")
    try:
        result = main(mode)
    except Exception as e:
        if mode == "auto":
            sys.stderr.write(f"bench auto failed ({e}); retrying tiny\n")
            result = run_llama("tiny")
        else:
            raise
    print(json.dumps(result))
